"""Parameter-server process.

The role of `src/kvstore/kvstore_dist_server.h:155-559` (KVStoreDistServer):
holds the authoritative copy of its key (ranges), merges sync pushes from
all workers, runs the optimizer server-side when one has been shipped over
(`DataHandleDefault`).  Multiple servers range-shard the key space like the
reference (`kvstore_dist.h:44` + `MXNET_KVSTORE_BIGARRAY_BOUND`): the root
server doubles as the scheduler (secondary servers register their address
here, workers fetch the list), each key slice travels under its TRUE key —
a server only ever owns its own range.  In collective mode the servers
carry control traffic only; gradients ride the TPU ICI mesh.

Sync semantics (`dist_sync`): each key carries a version counter equal to
the number of completed aggregation rounds.  A push contributes to the
current round; the round applies (updater or overwrite-with-sum) when all
`num_workers` contributions arrive.  A worker's pull waits until the
version reaches its own completed-push count, which reproduces the
reference guarantee that a pull issued after a push observes the round
that push joined (`kvstore_dist_server.h` DataHandleDefault + Response).

Async (`dist_async`): every push applies immediately (`DataHandleAsync`).
"""
from __future__ import annotations

import os
import pickle
import socketserver
import threading

import numpy as np

from .membership import MembershipTable
from .transport import recv_msg, send_msg
from ..analysis import locks as _locks
from ..resilience import faults as _faults

# idempotent reads: re-executing a resend is safe and cheaper than
# caching replies that can carry whole key-range arrays ("hb" and
# "members" are idempotent too — a re-executed heartbeat just refreshes
# the same liveness timestamp)
_READ_CMDS = frozenset({"pull", "server_list", "get_optimizer_states",
                        "hb", "members", "metrics", "embed_pull"})


class _State:
    def __init__(self, num_workers, num_servers=1):
        self.num_workers = num_workers
        self.num_servers = num_servers
        self.cond = _locks.make_condition(name="dist.server")
        self.store = {}          # key -> np.ndarray
        self.version = {}        # key -> completed rounds
        # key -> list of open rounds, each {"sum": array, "got": set(ranks)};
        # a worker's nth push joins round n (ps-lite timestamp semantics:
        # two pushes from one worker are two rounds, each still waiting for
        # every other worker)
        self.agg = {}
        self.updater = None
        self.barrier_count = 0
        self.barrier_gen = 0
        self.next_rank = 0
        self.stopped = 0
        self.servers = {}        # server_id (>=1) -> (host, port); root = 0
        # at-most-once RPC shell: a channel's requests are serial, but a
        # reconnect's re-handshake (register) can land BETWEEN a dropped
        # reply and its resend, so each client keeps its last few
        # (seq -> reply) entries — a resend after a mid-message drop
        # replays the cached reply instead of re-applying the push
        self.client_replies = {}   # client id -> {seq: reply} (last 4)
        self.client_inflight = set()   # (client, seq) being processed —
        # keyed by the PAIR: a reconnect's re-handshake (same client,
        # new seq) must not clobber a still-executing request's marker
        self.crashed = False       # fault-injected crash: refuse everything
        # elastic membership (the root server doubles as the pod
        # coordinator): built lazily on the first hb/shrink so plain
        # non-supervised runs never pay for it.  `epoch` mirrors the
        # table's epoch for cheap fencing inside kvstore waits.
        self.membership = None
        self.epoch = 0
        # sharded sparse-embedding tier (embedding/sharded.py): this
        # server hosts one ROW SHARD per table — only its own rows, the
        # table never materializes densely anywhere.
        # table -> {"rows": np [local_rows, dim], "ids": global row ids
        # this shard owns (sorted), "id_pos": id -> local position,
        # "version": applied pushes, "pushed"/"pulled": row counters}
        self.embed = {}


class ParameterServer:
    """Threaded TCP parameter server; one handler thread per worker."""

    def __init__(self, host="127.0.0.1", port=0, num_workers=None,
                 num_servers=None):
        self.num_workers = int(num_workers if num_workers is not None
                               else os.environ.get("DMLC_NUM_WORKER", 1))
        self.num_servers = int(num_servers if num_servers is not None
                               else os.environ.get("DMLC_NUM_SERVER", 1))
        self._state = _State(self.num_workers, self.num_servers)
        state = self._state
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (EOFError, ConnectionError, OSError):
                        break
                    if state.crashed:
                        break     # "dead" server: close without replying
                    try:
                        reply = outer._handle(msg)
                    except _faults.FaultInjected as exc:
                        if exc.kind == "crash":
                            outer._simulate_crash()
                        break     # connection dies mid-request, no reply
                    except (ConnectionError, OSError):
                        break     # injected/real drop: close, no reply
                    except Exception as exc:
                        # injected 'error' faults and real dispatch bugs
                        # become error replies — a handler thread dying
                        # with no reply would wedge the worker instead
                        reply = {"error": f"server dispatch failed: "
                                          f"{exc!r}",
                                 "seq": msg.get("seq")}
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        break     # client dropped while we replied
                    if msg.get("cmd") == "stop":
                        break

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        try:
            self._server = Server((host, port), Handler)
        except OSError as e:
            # never silently widen the bind surface: the transport carries
            # pickle, so binding all interfaces on a multi-homed host would
            # expose code execution to anything that can reach the port
            raise OSError(
                f"parameter server cannot bind {host}:{port} ({e}). Set "
                "DMLC_PS_ROOT_URI to an address bindable on this machine "
                "(e.g. the host's private interface IP), or 0.0.0.0 "
                "explicitly if you really mean all interfaces.") from e
        self.port = self._server.server_address[1]
        self._thread = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="mx-ps-server")
        self._thread.start()
        return self

    def serve_forever(self):
        if self._thread is None:
            self.start()
        st = self._state
        with st.cond:
            st.cond.wait_for(lambda: st.stopped >= st.num_workers)
        self.shutdown()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()

    def _simulate_crash(self):
        """Fault-injected server death: stop accepting, close the listen
        socket (new connects get refused), and let every live handler
        thread break on its next request — the process-kill failure mode
        without killing the test process."""
        st = self._state
        with st.cond:
            if st.crashed:
                return
            st.crashed = True
            st.cond.notify_all()
        threading.Thread(target=self.shutdown, daemon=True,
                         name="mx-ps-crash-shutdown").start()

    # -- request dispatch ----------------------------------------------------
    def _handle(self, msg):
        """The at-most-once shell around `_dispatch`: replays the cached
        reply for a resent (client, seq) — a reconnect after a dropped
        push must never apply the push twice — and echoes `seq` so the
        client can discard stale frames from timed-out requests."""
        st = self._state

        def _cached(cache):
            if cache is not None and seq in cache:
                reply = dict(cache[seq])
                reply["seq"] = seq
                reply["duplicate"] = True
                return reply
            return None

        client, seq = msg.get("client"), msg.get("seq")
        cmd = msg.get("cmd")
        # read-only commands are safely re-executable and their replies
        # can carry large arrays: no dedup shell, no reply caching
        dedup = client is not None and seq is not None \
            and cmd not in _READ_CMDS
        if dedup:
            with st.cond:
                dup = _cached(st.client_replies.get(client))
                if dup is not None:
                    return dup
                if (client, seq) in st.client_inflight:
                    # a handler thread on the DROPPED connection is still
                    # processing this request: wait for its outcome as
                    # long as the client itself would
                    from .. import config as _config
                    st.cond.wait_for(
                        lambda: (client, seq) not in st.client_inflight,
                        timeout=float(
                            _config.get("MXNET_PS_REQUEST_TIMEOUT")))
                    dup = _cached(st.client_replies.get(client))
                    if dup is not None:
                        return dup
                    return {"error": f"request seq {seq} is still in "
                                     "flight on another connection",
                            "seq": seq}
                st.client_inflight.add((client, seq))
        reply = None
        try:
            _faults.fire("server.dispatch", cmd=cmd)
            from ..obs import trace as _obs_trace
            with _obs_trace.server_span(msg, f"server.{cmd}",
                                        cat="kvstore"):
                reply = self._dispatch(msg)
        finally:
            if dedup:
                # caching the reply and clearing inflight must be ONE
                # critical section: a resender woken by the notify must
                # find the cached reply already there
                with st.cond:
                    if reply is not None:
                        # 'stop' is cached too: a resent stop whose reply
                        # was dropped must NOT double-increment the
                        # shutdown quorum (the entry is a few bytes and
                        # the client is gone anyway)
                        cache = st.client_replies.setdefault(client, {})
                        cache[seq] = reply
                        while len(cache) > 4:
                            del cache[min(cache)]
                    st.client_inflight.discard((client, seq))
                    st.cond.notify_all()
        if isinstance(reply, dict) and seq is not None:
            reply["seq"] = seq
        return reply

    def _membership(self):
        """The pod membership table (root server = coordinator), built on
        first use with the configured heartbeat deadline."""
        st = self._state
        with st.cond:
            if st.membership is None:
                from .. import config as _config
                st.membership = MembershipTable(
                    st.num_workers,
                    deadline_s=float(
                        _config.get("MXNET_SUPERVISOR_DEADLINE_S")))
                st.membership.epoch = st.epoch
            return st.membership

    def _reset_world(self, result):
        """Shrink commit: the new epoch starts from a CLEAN kvstore — the
        authoritative state is the survivors' last checkpoint, which the
        resumed fit re-pushes exactly like a fresh launch (the PR 5
        restarted-empty-server machinery).  Keeping the old store would be
        worse than useless: it holds post-checkpoint updates and
        half-aggregated rounds with dead-host contributions."""
        st = self._state
        with st.cond:
            st.epoch = result["epoch"]
            st.num_workers = result["world_size"]
            st.store.clear()
            st.version.clear()
            st.agg.clear()
            # release any barrier waiters from the old epoch (their reply
            # lands on dead or about-to-restart channels either way)
            st.barrier_count = 0
            st.barrier_gen += 1
            st.next_rank = 0
            st.client_replies.clear()
            st.cond.notify_all()

    def _dispatch(self, msg):
        cmd = msg.get("cmd")
        st = self._state
        if cmd == "register":
            # epoch fence: once a shrink committed, a register from a
            # host that missed it (its env still carries the old epoch)
            # must be refused — its rank could collide with a survivor's
            # new rank and corrupt post-shrink state
            if st.membership is not None:
                stale = st.membership.check_epoch(msg.get("epoch", 0))
                if stale is not None and msg.get("role") == "worker":
                    return stale
            with st.cond:
                rank = msg.get("rank")
                if rank is None:
                    rank = st.next_rank
                st.next_rank = max(st.next_rank, rank + 1)
            return {"rank": rank, "num_workers": st.num_workers,
                    "num_servers": st.num_servers, "epoch": st.epoch}

        if cmd == "hb":
            return self._membership().heartbeat(
                msg["rank"], msg.get("epoch", 0), step=msg.get("step"),
                step_time=msg.get("step_time"))

        if cmd == "metrics":
            # the scrape plane: this server process's registry snapshot
            from ..obs.scrape import metrics_reply
            return metrics_reply()

        if cmd == "members":
            return {"ok": True, "view": self._membership().view()}

        if cmd == "shrink":
            from .. import config as _config
            # the barrier must outlast a peer whose collective watchdog
            # has not fired yet: survivors enter the hang within a step
            # of each other, so the worst-case stagger is one full
            # watchdog deadline (plus heartbeat slack) — a 30s barrier
            # under a 120s watchdog would fence out healthy survivors
            deadline = max(
                float(_config.get("MXNET_SUPERVISOR_SHRINK_BARRIER_S")),
                float(_config.get("MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S"))
                + 2 * float(_config.get("MXNET_SUPERVISOR_DEADLINE_S")))
            return self._membership().propose_shrink(
                msg["rank"], msg.get("epoch", 0), deadline_s=deadline,
                on_commit=self._reset_world)

        if cmd == "register_server":
            # a secondary server announces its address; the root doubles
            # as the reference's scheduler (ps-lite van) for this exchange
            with st.cond:
                st.servers[int(msg["server_id"])] = (msg["host"],
                                                     int(msg["port"]))
                st.cond.notify_all()
            return {"ok": True}

        if cmd == "server_list":
            want = set(range(1, st.num_servers))
            with st.cond:
                ok = st.cond.wait_for(
                    lambda: want <= set(st.servers), timeout=300)
                if not ok:
                    missing = sorted(want - set(st.servers))
                    return {"error": "timed out waiting for secondary "
                                     f"server id(s) {missing} to register "
                                     "(launch them with DMLC_SERVER_ID in "
                                     f"1..{st.num_servers - 1})"}
                return {"servers": [list(st.servers[i])
                                    for i in range(1, st.num_servers)],
                        "num_servers": st.num_servers}

        if cmd == "init":
            with st.cond:
                for k, v in zip(msg["keys"], msg["values"]):
                    if k not in st.store:
                        st.store[k] = np.asarray(v)
                        st.version[k] = 0
                st.cond.notify_all()
            return {"ok": True}

        if cmd == "push":
            from .compression import is_packed, unpack_2bit
            raw = msg["value"]
            v = unpack_2bit(raw) if is_packed(raw) else np.asarray(raw)
            k, sync = msg["key"], msg["sync"]
            rank = msg.get("rank", 0)
            with st.cond:
                if k not in st.store:
                    return {"error": f"Key {k} has not been initialized"}
                if sync:
                    rounds = st.agg.setdefault(k, [])
                    # this worker's next round: first it hasn't contributed to
                    ent = next((r for r in rounds if rank not in r["got"]),
                               None)
                    if ent is None:
                        ent = {"sum": np.zeros_like(st.store[k],
                                                    dtype=v.dtype),
                               "got": set()}
                        rounds.append(ent)
                    ent["sum"] = ent["sum"] + v
                    ent["got"].add(rank)
                    # apply completed rounds in order from the head
                    while rounds and len(rounds[0]["got"]) >= st.num_workers:
                        self._apply(k, rounds.pop(0)["sum"])
                        st.version[k] += 1
                        st.cond.notify_all()
                    if not rounds:
                        del st.agg[k]
                else:
                    self._apply(k, v)
                    st.version[k] += 1
                    st.cond.notify_all()
                return {"version": st.version[k]}

        if cmd == "pull":
            k = msg["key"]
            min_version = msg.get("min_version", 0)
            with st.cond:
                if k not in st.store:
                    return {"error": f"Key {k} has not been initialized"}
                # epoch fence: a shrink commit resets the store mid-wait —
                # this round can never complete, so the waiter must be
                # released with an error instead of idling out the 300s
                epoch0 = st.epoch
                ok = st.cond.wait_for(
                    lambda: st.version.get(k, 0) >= min_version
                    or st.epoch != epoch0, timeout=300)
                if st.epoch != epoch0:
                    return {"error": f"epoch fenced: pull({k}) was waiting "
                                     f"across a shrink commit (epoch "
                                     f"{epoch0} -> {st.epoch}); re-register "
                                     "and resume from the checkpoint"}
                if not ok:
                    return {"error": f"pull({k}) timed out waiting for "
                                     f"version {min_version}"}
                return {"value": st.store[k], "version": st.version[k]}

        if cmd == "barrier":
            with st.cond:
                st.barrier_count += 1
                gen = st.barrier_gen
                if st.barrier_count >= st.num_workers:
                    st.barrier_count = 0
                    st.barrier_gen += 1
                    st.cond.notify_all()
                else:
                    ok = st.cond.wait_for(lambda: st.barrier_gen > gen,
                                          timeout=300)
                    if not ok:
                        # withdraw this arrival so the generation count
                        # stays consistent, and fail loudly: a missing
                        # worker must not let the others "pass" the barrier
                        st.barrier_count -= 1
                        return {"error": "barrier timed out waiting for "
                                         "all workers"}
            return {"ok": True}

        if cmd == "set_optimizer":
            # reference ships the optimizer with MXKVStoreSendCommmandToServers
            # (kvstore_dist.h SendCommandToServers → server CommandHandle)
            from .. import optimizer as opt
            optimizer = pickle.loads(msg["optimizer"])
            with st.cond:
                st.updater = opt.get_updater(optimizer)
            return {"ok": True}

        if cmd == "get_optimizer_states":
            # checkpoint plane: a server-side optimizer's slots (momentum /
            # Adam moments for THIS server's key ranges) travel back to the
            # worker over the control channel, so an elastic checkpoint
            # captures them without a dedicated server filesystem
            with st.cond:
                if st.updater is None:
                    return {"states": None}
                return {"states": st.updater.get_states(
                    dump_optimizer=bool(msg.get("dump_optimizer")))}

        if cmd == "set_optimizer_states":
            with st.cond:
                if st.updater is None:
                    return {"error": "set_optimizer_states: no optimizer "
                                     "installed on this server (send "
                                     "set_optimizer first)"}
                st.updater.set_states(msg["states"])
            return {"ok": True}

        if cmd == "profiler":
            # server-side profiling commands (reference kvstore.py
            # set_server_profiler_state/dump forwarded through
            # MXKVStoreSendCommmandToServers): drive THIS process's
            # profiler so server-side aggregation cost is observable
            from .. import profiler as _profiler
            action = msg.get("action")
            try:
                if action == "set_config":
                    _profiler.set_config(**msg.get("config", {}))
                elif action == "set_state":
                    _profiler.set_state(msg.get("state", "stop"))
                elif action == "dump":
                    _profiler.dump()
                else:
                    return {"error": f"unknown profiler action {action!r}"}
            except Exception as e:
                # every dispatch branch replies; a raise here would kill
                # the handler thread with no reply and stall the worker
                return {"error": f"server profiler {action} failed: {e!r}"}
            return {"ok": True, "state": _profiler.state()}

        if cmd == "embed_init":
            # one ROW SHARD of a sharded embedding table lands here: the
            # worker ships the global row ids this server owns plus either
            # the initial values or a (seed, scale) recipe — the table as
            # a whole never exists densely in any single process
            table = msg["table"]
            with st.cond:
                dim = int(msg["dim"])
                dtype = np.dtype(msg.get("dtype", "float32"))
                if msg.get("ids") is not None:
                    # hash partition: an explicit (sorted) id set
                    ids = np.asarray(msg["ids"], dtype=np.int64)
                    ent = {"mode": "set", "ids": ids,
                           "id_pos": {int(i): p
                                      for p, i in enumerate(ids)}}
                    n, seed_salt = len(ids), int(ids[0]) if len(ids) else 0
                else:
                    # range partition: one contiguous interval — local
                    # position is id - row_start, no per-id index needed
                    lo, hi = int(msg["row_start"]), int(msg["row_end"])
                    ent = {"mode": "range", "row_start": lo, "row_end": hi}
                    n, seed_salt = hi - lo, lo
                old = st.embed.get(table)
                if old is not None:
                    if (old["mode"] != ent["mode"]
                            or old["rows"].shape != (n, dim)
                            or (ent["mode"] == "range"
                                and (old["row_start"], old["row_end"])
                                != (ent["row_start"], ent["row_end"]))
                            or (ent["mode"] == "set"
                                and not np.array_equal(old["ids"],
                                                       ent["ids"]))):
                        # the worker and this server disagree about
                        # shard ownership — a silent ack would leave the
                        # old rows serving under the new partition rules
                        return {"error": f"embed_init: table {table!r} "
                                         "already exists on this server "
                                         "with a different shard spec — "
                                         "refusing to keep stale rows "
                                         f"(have {old['rows'].shape}, "
                                         f"init asked for {(n, dim)})"}
                    if msg.get("values") is None:
                        # same spec, no payload: idempotent re-init
                        # (transport retry) — the rows already live here
                        return {"ok": True, "rows": len(old["rows"]),
                                "version": old["version"]}
                    # explicit values on an existing table: a checkpoint
                    # restore through replace_shard landed on a standby/
                    # previously-initialized server — overwrite, a silent
                    # no-op ack would defeat the recovery path
                    old["rows"] = np.asarray(
                        msg["values"],
                        dtype=old["rows"].dtype).reshape(n, dim)
                    old["version"] += 1
                    st.cond.notify_all()
                    return {"ok": True, "rows": n,
                            "version": old["version"]}
                if msg.get("values") is not None:
                    rows = np.asarray(msg["values"], dtype=dtype)
                else:
                    rng = np.random.default_rng(
                        [int(msg.get("seed", 0)), seed_salt])
                    rows = (rng.standard_normal((n, dim))
                            * float(msg.get("scale", 0.01))).astype(dtype)
                ent.update(rows=rows, version=0, pushed=0, pulled=0)
                st.embed[table] = ent
                st.cond.notify_all()
            return {"ok": True, "rows": n, "version": 0}

        if cmd in ("embed_push", "embed_pull"):
            table = msg["table"]
            with st.cond:
                ent = st.embed.get(table)
                if ent is None:
                    return {"error": f"embedding table {table!r} has not "
                                     "been initialized on this server"}
                ids = np.asarray(msg["ids"], dtype=np.int64)
                if ent["mode"] == "range":
                    local = ids - ent["row_start"]
                    bad = (local < 0) | (local >= len(ent["rows"]))
                    if bad.any():
                        return {"error": f"embedding table {table!r}: row "
                                         f"{int(ids[bad][0])} is outside "
                                         "this shard's range "
                                         f"[{ent['row_start']}, "
                                         f"{ent['row_end']}) (worker/"
                                         "server partition rules "
                                         "disagree)"}
                else:
                    pos = ent["id_pos"]
                    try:
                        local = np.fromiter((pos[int(i)] for i in ids),
                                            dtype=np.int64, count=len(ids))
                    except KeyError as e:
                        return {"error": f"embedding table {table!r}: row "
                                         f"{e.args[0]} is not owned by "
                                         "this shard (worker/server "
                                         "partition rules disagree)"}
                if cmd == "embed_pull":
                    ent["pulled"] += len(local)
                    return {"values": ent["rows"][local],
                            "version": ent["version"]}
                vals = np.asarray(msg["values"],
                                  dtype=ent["rows"].dtype)
                if msg.get("op") == "assign":
                    # checkpoint restore / weight swap: overwrite rows
                    # (a prior lazy update left rows as a read-only
                    # device-array view — rematerialize writable first)
                    if not ent["rows"].flags.writeable:
                        ent["rows"] = np.array(ent["rows"])
                    ent["rows"][local] = vals
                elif st.updater is None:
                    return {"error": f"embed_push({table!r}): no "
                                     "optimizer installed on this server "
                                     "(send set_optimizer first, or push "
                                     "with op='assign')"}
                else:
                    # lazy row-sparse optimizer step over the LOCAL slice:
                    # the grad travels as (rows, values) and optimizer.py's
                    # lazy SGD/Adam paths gather/update/scatter only the
                    # touched rows — identical math to a worker-side
                    # row_sparse update
                    from ..ndarray.ndarray import array
                    from ..ndarray.sparse import RowSparseNDArray
                    weight = array(ent["rows"])
                    grad = RowSparseNDArray(vals, local, ent["rows"].shape)
                    st.updater(f"embed:{table}", grad, weight)
                    ent["rows"] = weight.asnumpy()
                ent["pushed"] += len(local)
                ent["version"] += 1
                st.cond.notify_all()
                # the post-update rows ride the reply so the worker's
                # hot-row cache refreshes in place instead of
                # invalidating — steady-state training lookups then
                # never leave HBM
                return {"ok": True, "version": ent["version"],
                        "values": ent["rows"][local]}

        if cmd == "stop":
            with st.cond:
                st.stopped += 1
                st.cond.notify_all()
            return {"ok": True}

        return {"error": f"unknown command {cmd!r}"}

    def _apply(self, k, merged):
        """Apply one completed round: server-side optimizer step, or store
        the aggregated gradient for worker-side updates (update_on_kvstore
        False — reference `kvstore_dist_server.h` both paths)."""
        st = self._state
        if st.updater is None:
            st.store[k] = np.asarray(merged)
            return
        from ..ndarray.ndarray import NDArray, array
        weight = array(st.store[k])
        grad = array(np.asarray(merged, dtype=st.store[k].dtype))
        ukey = int(k) if str(k).isdigit() else k
        st.updater(ukey, grad, weight)
        st.store[k] = weight.asnumpy()


def register_with_root(root_host, root_port, server_id, host, port):
    """Announce a secondary server's address to the root/scheduler."""
    from .transport import Channel
    chan = Channel(root_host, root_port)
    try:
        reply = chan.request({"cmd": "register_server",
                              "server_id": int(server_id),
                              "host": host, "port": int(port)})
        if "error" in reply:
            raise RuntimeError(reply["error"])
    finally:
        chan.close()


def main():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")  # servers never touch chips
    except Exception:
        pass
    server_id = int(os.environ.get("DMLC_SERVER_ID", 0))
    root_host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
    if server_id == 0:
        server = ParameterServer(host=root_host, port=root_port)
    else:
        # secondary key-range server: bind any port, tell the root
        server = ParameterServer(
            host=os.environ.get("DMLC_SERVER_HOST", "127.0.0.1"),
            port=int(os.environ.get("DMLC_SERVER_PORT", 0)))
        server.start()
        register_with_root(root_host, root_port, server_id,
                           os.environ.get("DMLC_SERVER_HOST", "127.0.0.1"),
                           server.port)
    server.serve_forever()


if __name__ == "__main__":
    main()
