"""2-bit gradient wire packing (reference
`src/kvstore/gradient_compression.h:52-134`).

The reference packs 16 two-bit codes into each 32-bit word before the
ps-lite ZPush; here 4 codes pack into each byte — same 16× density over
fp32.  Quantization itself (threshold + error-feedback residuals) happens
device-side in `KVStore._compress`; this module is only the host-side wire
codec: a {-thr, 0, +thr} array becomes ceil(n/4) bytes on the socket, and
the server expands back to dense before accumulating.

Code map (2 bits): 0 -> 0.0, 1 -> +threshold, 2 -> -threshold.
"""
from __future__ import annotations

import numpy as np

__all__ = ["pack_2bit", "unpack_2bit", "is_packed"]


def pack_2bit(q: np.ndarray, threshold: float) -> dict:
    """Encode a quantized {-thr, 0, +thr} float array as a 2-bit stream."""
    flat = np.asarray(q, dtype=np.float32).ravel()
    codes = np.zeros(flat.size, dtype=np.uint8)
    codes[flat > 0] = 1
    codes[flat < 0] = 2
    pad = (-codes.size) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    packed = (codes[0::4] | (codes[1::4] << 2) | (codes[2::4] << 4) |
              (codes[3::4] << 6))
    return {"packed2bit": packed, "shape": tuple(q.shape),
            "threshold": float(threshold), "dtype": str(q.dtype)}


def is_packed(value) -> bool:
    return isinstance(value, dict) and "packed2bit" in value


def unpack_2bit(msg: dict) -> np.ndarray:
    """Expand a packed 2-bit stream back to the dense quantized array."""
    packed = np.asarray(msg["packed2bit"], dtype=np.uint8)
    shape = tuple(msg["shape"])
    thr = float(msg["threshold"])
    n = int(np.prod(shape)) if shape else 1
    codes = np.empty((packed.size, 4), dtype=np.uint8)
    codes[:, 0] = packed & 3
    codes[:, 1] = (packed >> 2) & 3
    codes[:, 2] = (packed >> 4) & 3
    codes[:, 3] = (packed >> 6) & 3
    codes = codes.ravel()[:n]
    out = np.zeros(n, dtype=np.dtype(msg.get("dtype", "float32")))
    out[codes == 1] = thr
    out[codes == 2] = -thr
    return out.reshape(shape)
