"""Framed message transport for the parameter-server protocol.

The ps-lite Van (`ps-lite/src/van.cc`) moves zero-copy protobuf messages
over ZMQ; here a message is one length-prefixed frame on a TCP stream:

    [8-byte big-endian length][payload]

The payload is a small header dict plus raw ndarray bytes, serialized with
pickle protocol 5 (out-of-band buffers keep large arrays as single
memoryview copies — the practical equivalent of ps-lite's zero-copy SArray
for a localhost/DCN transport).  The channel assumes a private cluster
network (ps-lite's trust model), but because pickle deserialization is
code execution, setting ``MXNET_PS_HMAC_KEY`` (same value on every node)
adds an HMAC-SHA256 tag over the pickle frame that is verified BEFORE
deserialization — a cheap authentication fence for shared networks.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import random as _random
import socket
import struct
import time

_tsan = None   # analysis.tsan, memoized on first recv (lazy: low-level module)
_trace = None  # obs.trace, memoized on first request (lazy: low-level module)


def _obs_trace():
    global _trace
    if _trace is None:
        from ..obs import trace
        _trace = trace
    return _trace

_LEN = struct.Struct(">Q")
_TAG_LEN = 32


def _hmac_key():
    k = os.environ.get("MXNET_PS_HMAC_KEY", "")
    return k.encode() if k else None


def parse_endpoint(spec, default_host="127.0.0.1"):
    """``"host:port"`` / ``":port"`` / ``"port"`` -> ``(host, port)``.

    The one parser for remote endpoints handed to the serving fleet
    (host registries name hostd agents by endpoint) and any CLI taking
    a peer address — so every front end accepts the same spellings."""
    text = str(spec).strip()
    host, sep, port = text.rpartition(":")
    if not sep:
        host, port = "", text
    host = host or default_host
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(f"invalid endpoint {spec!r} (want host:port)") \
            from None


def send_msg(sock: socket.socket, obj) -> None:
    buffers = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    raws = [b.raw() for b in buffers]
    # frame: payload length, out-of-band buffer count, payload, each buffer
    # prefixed with its own length, then [HMAC tag] when keyed.  The tag
    # covers the pickle AND every out-of-band buffer (protocol 5 ships the
    # actual ndarray bytes out-of-band — leaving them unauthenticated would
    # let a peer flip gradient bytes behind a valid tag).
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(_LEN.pack(len(raws)))
    sock.sendall(payload)
    key = _hmac_key()
    mac = _hmac.new(key, payload, hashlib.sha256) if key is not None else None
    for r in raws:
        sock.sendall(_LEN.pack(len(r)))
        sock.sendall(r)
        if mac is not None:
            mac.update(r)
    if mac is not None:
        sock.sendall(mac.digest())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise EOFError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket):
    plen = _LEN.unpack(_recv_exact(sock, 8))[0]
    nbuf = _LEN.unpack(_recv_exact(sock, 8))[0]
    payload = _recv_exact(sock, plen)
    key = _hmac_key()
    mac = _hmac.new(key, payload, hashlib.sha256) if key is not None else None
    bufs = []
    for _ in range(nbuf):
        blen = _LEN.unpack(_recv_exact(sock, 8))[0]
        buf = _recv_exact(sock, blen)
        if mac is not None:
            mac.update(buf)
        bufs.append(buf)
    if mac is not None:
        tag = _recv_exact(sock, _TAG_LEN)
        if not _hmac.compare_digest(tag, mac.digest()):
            raise ConnectionError(
                "transport: HMAC verification failed — peer does not hold "
                "MXNET_PS_HMAC_KEY; refusing to deserialize")
    return pickle.loads(payload, buffers=bufs)


class Channel:
    """One request/response channel to the server (worker side).

    Requests ride sequence-numbered, client-tagged frames.  Three failure
    modes are handled instead of surfaced raw:

    * **startup race** — workers and server launch concurrently (ps-lite
      nodes retry until the scheduler is up): connect retries with
      exponential backoff + jitter under a ``connect_wait`` deadline;
    * **slow (not dead) server** — a request that exceeds ``timeout``
      raises, but the channel stays USABLE: when the stale reply finally
      arrives it is discarded by sequence number on the next request,
      instead of being misdelivered as that request's answer (the old
      "timeout desyncs the channel" failure);
    * **mid-message connection drop** — the request is resent over a
      fresh connection under the retry policy.  The server deduplicates
      by ``(client, seq)`` and replays its cached reply, so a resend can
      never double-apply a push (at-most-once application, exactly-once
      observation).
    """

    _CLIENT_COUNTER = [0]

    def __init__(self, host: str, port: int, timeout: float | None = None,
                 connect_wait: float | None = None, retry=None):
        from .. import config as _config
        from ..resilience import RetryPolicy, faults as _faults
        self._faults = _faults
        self.host, self.port = host, int(port)  # for error reporting
        # the timeout must exceed the server's longest internal wait (300s
        # sync-round/barrier waits); it bounds a dead/partitioned server
        self._timeout = float(timeout) if timeout is not None else \
            float(_config.get("MXNET_PS_REQUEST_TIMEOUT"))
        self._connect_wait = float(connect_wait) if connect_wait is not None \
            else float(_config.get("MXNET_PS_CONNECT_WAIT"))
        # mid-request reconnects use a SHORTER window than the startup
        # race: at startup the server may legitimately not exist yet; a
        # reconnect means it just died, and failover should be diagnosed
        # in seconds, not minutes
        self._reconnect_wait = min(
            self._connect_wait, float(_config.get("MXNET_PS_RECONNECT_WAIT")))
        self._retry = retry or RetryPolicy(
            max_attempts=int(_config.get("MXNET_PS_MAX_RETRIES")),
            base_delay=0.05, max_delay=2.0)
        Channel._CLIENT_COUNTER[0] += 1
        self.client_id = "%d.%d.%d" % (os.getpid(), id(self) & 0xffffff,
                                       Channel._CLIENT_COUNTER[0])
        self._seq = 0
        self.resends = 0           # observability: idempotent resends
        self.discarded_stale = 0   # stale replies dropped by seq
        self.on_reconnect = None   # re-handshake hook (kvstore_dist sets it)
        self._sock = None
        self._closed = False
        self._connect(self._connect_wait)

    def _connect(self, wait):
        rng = _random.Random(self._retry.seed)
        deadline = time.monotonic() + wait
        attempt = 0
        while True:
            try:
                self._faults.fire("transport.connect", host=self.host,
                                  port=self.port)
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=10.0)
                break
            except (ConnectionRefusedError, socket.timeout, OSError) as exc:
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"could not connect to {self.host}:{self.port} "
                        f"within {wait:g}s ({type(exc).__name__}: {exc})"
                        ) from exc
                time.sleep(min(self._retry.delay(attempt, rng),
                               max(deadline - time.monotonic(), 0.0)))
                attempt += 1
        self._sock.settimeout(self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _read_reply(self, expect):
        """Next reply for sequence number `expect`; frames answering
        other (timed-out) requests are discarded — they are the stale
        bytes that used to poison the channel.  The expected seq is
        explicit because a reconnect's re-handshake consumes newer
        sequence numbers while the resent request keeps its original one
        (the server dedups on the exact (client, seq) pair)."""
        while True:
            self._faults.fire("transport.recv", sock=self._sock)
            # mxtsan: a socket wait is the blocking call the patched
            # primitives cannot see — report it so "recv while holding
            # a contended lock" becomes a finding, not a stall.  The
            # module is memoized and the call gated on the sanitizer
            # being installed: the off path pays one boolean test
            global _tsan
            if _tsan is None:
                from ..analysis import tsan
                _tsan = tsan
            if _tsan._installed:
                _tsan.note_blocking("socket.recv",
                                    detail=f"{self.host}:{self.port}")
            reply = recv_msg(self._sock)
            seq = reply.get("seq") if isinstance(reply, dict) else None
            if seq is None or seq == expect:
                return reply
            self.discarded_stale += 1

    def request(self, obj):
        """One request/reply round trip.  Connection-level failures resend
        under the retry policy (safe: the server dedups by client+seq);
        a timeout raises but leaves the channel consistent.

        When distributed tracing is on (``MXNET_OBS_TRACE``) the frame
        carries a ``tr`` span context — the server side's handling span
        parents to this request's rpc span, in another process.  A
        resend reuses the ORIGINAL frame (and span id), so a dedup
        replay still joins the same trace."""
        self._seq += 1
        msg = dict(obj)
        msg["seq"] = self._seq
        msg["client"] = self.client_id
        sp = _obs_trace().rpc_span(msg, f"{self.host}:{self.port}")
        self._last_frame = msg
        try:
            return self._send_framed(msg)
        finally:
            sp.end()

    def resend_last(self):
        """Retry the most recent request with its ORIGINAL sequence
        number.  The failover layer's outer retries go through here so a
        resend that reaches a server which already applied the request
        hits the (client, seq) dedup cache — a fresh `request()` would
        stamp a new seq and could double-apply a push."""
        return self._send_framed(self._last_frame)

    def _send_framed(self, msg):
        if self._closed:
            raise ConnectionError(
                f"channel to {self.host}:{self.port} is closed")
        delays = self._retry.delays()
        while True:
            try:
                if self._sock is None:
                    self._connect(self._reconnect_wait)
                    if self.on_reconnect is not None:
                        self.on_reconnect(self)
                self._faults.fire("transport.send", cmd=msg.get("cmd"),
                                  sock=self._sock)
                send_msg(self._sock, msg)
                return self._read_reply(msg["seq"])
            except socket.timeout:
                # the timeout may have fired MID-FRAME (partial reply
                # read, partial send): the stream position is no longer
                # trustworthy, so drop the socket — the next request
                # reconnects, and resends stay safe because the server
                # dedups by (client, seq)
                self._drop_sock()
                raise TimeoutError(
                    f"request {msg.get('cmd')!r} to {self.host}:{self.port} "
                    f"timed out after {self._timeout:g}s; the server is "
                    "slow or wedged (socket dropped — the channel "
                    "reconnects on the next request and resends are "
                    "deduplicated by sequence number)")
            except (ConnectionError, EOFError, OSError) as exc:
                self._drop_sock()
                delay = next(delays, None)
                if delay is None:
                    raise
                self.resends += 1
                self._faults.note("retry", site="transport.send",
                                  cmd=msg.get("cmd"), attempt=self.resends,
                                  error=type(exc).__name__)
                time.sleep(delay)

    def bare_request(self, obj):
        """One un-retried round trip on the live socket (re-handshake
        hooks use this — they run INSIDE the retry loop)."""
        self._seq += 1
        msg = dict(obj)
        msg["seq"] = self._seq
        msg["client"] = self.client_id
        if self._closed or self._sock is None:
            raise ConnectionError(
                f"channel to {self.host}:{self.port} is closed")
        sp = _obs_trace().rpc_span(msg, f"{self.host}:{self.port}")
        try:
            send_msg(self._sock, msg)
            return self._read_reply(msg["seq"])
        finally:
            sp.end()

    def close(self):
        """Close for good: later requests fail fast instead of silently
        reconnecting (and re-registering) against whatever now owns the
        port."""
        self._closed = True
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None
