"""Framed message transport for the parameter-server protocol.

The ps-lite Van (`ps-lite/src/van.cc`) moves zero-copy protobuf messages
over ZMQ; here a message is one length-prefixed frame on a TCP stream:

    [8-byte big-endian length][payload]

The payload is a small header dict plus raw ndarray bytes, serialized with
pickle protocol 5 (out-of-band buffers keep large arrays as single
memoryview copies — the practical equivalent of ps-lite's zero-copy SArray
for a localhost/DCN transport).  The channel assumes a private cluster
network (ps-lite's trust model), but because pickle deserialization is
code execution, setting ``MXNET_PS_HMAC_KEY`` (same value on every node)
adds an HMAC-SHA256 tag over the pickle frame that is verified BEFORE
deserialization — a cheap authentication fence for shared networks.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import socket
import struct

_LEN = struct.Struct(">Q")
_TAG_LEN = 32


def _hmac_key():
    k = os.environ.get("MXNET_PS_HMAC_KEY", "")
    return k.encode() if k else None


def send_msg(sock: socket.socket, obj) -> None:
    buffers = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    raws = [b.raw() for b in buffers]
    # frame: payload length, out-of-band buffer count, payload, each buffer
    # prefixed with its own length, then [HMAC tag] when keyed.  The tag
    # covers the pickle AND every out-of-band buffer (protocol 5 ships the
    # actual ndarray bytes out-of-band — leaving them unauthenticated would
    # let a peer flip gradient bytes behind a valid tag).
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(_LEN.pack(len(raws)))
    sock.sendall(payload)
    key = _hmac_key()
    mac = _hmac.new(key, payload, hashlib.sha256) if key is not None else None
    for r in raws:
        sock.sendall(_LEN.pack(len(r)))
        sock.sendall(r)
        if mac is not None:
            mac.update(r)
    if mac is not None:
        sock.sendall(mac.digest())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise EOFError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket):
    plen = _LEN.unpack(_recv_exact(sock, 8))[0]
    nbuf = _LEN.unpack(_recv_exact(sock, 8))[0]
    payload = _recv_exact(sock, plen)
    key = _hmac_key()
    mac = _hmac.new(key, payload, hashlib.sha256) if key is not None else None
    bufs = []
    for _ in range(nbuf):
        blen = _LEN.unpack(_recv_exact(sock, 8))[0]
        buf = _recv_exact(sock, blen)
        if mac is not None:
            mac.update(buf)
        bufs.append(buf)
    if mac is not None:
        tag = _recv_exact(sock, _TAG_LEN)
        if not _hmac.compare_digest(tag, mac.digest()):
            raise ConnectionError(
                "transport: HMAC verification failed — peer does not hold "
                "MXNET_PS_HMAC_KEY; refusing to deserialize")
    return pickle.loads(payload, buffers=bufs)


class Channel:
    """One request/response channel to the server (worker side).

    Connection retries cover the server's startup window — workers and
    server launch concurrently (the reference tracker has the same race and
    the same answer: ps-lite nodes retry until the scheduler is up).
    """

    def __init__(self, host: str, port: int, timeout: float | None = 330.0,
                 connect_wait: float = 90.0):
        import time
        self.host, self.port = host, int(port)  # for error reporting
        deadline = time.monotonic() + connect_wait
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=10.0)
                break
            except (ConnectionRefusedError, socket.timeout, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.3)
        # the timeout must exceed the server's longest internal wait (300s
        # sync-round/barrier waits): shorter would cut a frame mid-stream
        # and desync the channel; it still bounds a dead/partitioned server
        self._sock.settimeout(timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request(self, obj):
        send_msg(self._sock, obj)
        return recv_msg(self._sock)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
