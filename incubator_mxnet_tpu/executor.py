"""Executor: a bound Symbol compiled to single XLA computations.

Re-expression of `src/executor/graph_executor.cc` (Bind/SimpleBind at
:1575/1606, Forward :63, Backward :76) for TPU.  Where the reference builds
per-node engine ops with a memory plan (`PlanMemory`) and fuses bulk segments,
here the *whole graph* is one `jax.jit`-compiled XLA program per
(train-mode, input-signature) — memory planning, fusion, and scheduling are
delegated to XLA (SURVEY.md §7 design stance).  The Forward/Backward split is
preserved: Forward runs the forward executable; Backward runs a combined
forward+vjp executable reusing the SAME rng key so stochastic ops (Dropout)
see identical masks in both passes, matching the reference's stored-mask
semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError, np_dtype
from .context import Context, current_context
from .ndarray.ndarray import NDArray
from .symbol.symbol import Symbol, graph_eval_fn

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, arg_arrays, grad_arrays, grad_req,
                 aux_arrays):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_arrays = list(arg_arrays)
        self.grad_arrays = list(grad_arrays)
        self.aux_arrays = list(aux_arrays)
        self.arg_dict = dict(zip(arg_names, self.arg_arrays))
        self.grad_dict = dict(zip(arg_names, self.grad_arrays))
        self.aux_dict = dict(zip(aux_names, self.aux_arrays))
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}
        self.outputs = []
        self._monitor_callback = None

        self._fns = {}      # is_train -> python graph fn
        self._fwd_jit = {}  # is_train -> jitted forward
        self._bwd_jit = None
        self._n_rng = 0
        self._last_key = None
        self._last_is_train = False

    # -- compilation ---------------------------------------------------------
    def _graph_fn(self, is_train):
        if is_train not in self._fns:
            fn, arg_nodes, aux_nodes, n_rng = graph_eval_fn(self._symbol,
                                                            is_train)
            self._n_rng = n_rng
            self._fns[is_train] = fn
        return self._fns[is_train]

    def _forward_jit(self, is_train):
        if is_train not in self._fwd_jit:
            fn = self._graph_fn(is_train)
            self._fwd_jit[is_train] = jax.jit(
                lambda args, aux, key: fn(args, aux, key))
        return self._fwd_jit[is_train]

    def _forward_res_jit(self):
        """Training forward that ALSO returns the vjp residuals, so
        `backward()` replays only the linearized backward pass — the
        reference reuses forward activations the same way
        (`graph_executor.cc:63,76` Forward stashes, Backward consumes).
        `jax.vjp`'s function is a `Partial` pytree whose leaves are the
        residual arrays: a jit can return it, and `_vjp_apply_jit`
        consumes it in a second program with no forward recompute."""
        if getattr(self, "_fwd_res", None) is None:
            fn = self._graph_fn(True)
            wrt_idx = [i for i, n in enumerate(self._symbol.list_arguments())
                       if self._grad_req.get(n, "null") != "null"]

            def run(args, aux, key):
                args = list(args)

                def f(wrt_vals):
                    for i, v in zip(wrt_idx, wrt_vals):
                        args[i] = v
                    outs, new_aux = fn(tuple(args), aux, key)
                    return outs, new_aux

                outs, vjp, new_aux = jax.vjp(
                    f, tuple(args[i] for i in wrt_idx), has_aux=True)
                return outs, new_aux, vjp

            self._fwd_res = jax.jit(run)
            self._bwd_wrt_idx = wrt_idx

            def apply(vjp, cts):
                (grads,) = vjp(cts)
                return grads

            self._vjp_apply_jit = jax.jit(apply)
        return self._fwd_res

    def _backward_jit(self):
        if self._bwd_jit is None:
            fn = self._graph_fn(True)
            wrt_idx = [i for i, n in enumerate(self._symbol.list_arguments())
                       if self._grad_req.get(n, "null") != "null"]

            def run(args, aux, key, out_grads):
                args = list(args)

                def f(wrt_vals):
                    for i, v in zip(wrt_idx, wrt_vals):
                        args[i] = v
                    outs, new_aux = fn(tuple(args), aux, key)
                    return outs, new_aux

                outs, vjp, new_aux = jax.vjp(f, tuple(args[i] for i in wrt_idx),
                                             has_aux=True)
                cts = tuple(
                    og if og is not None else jnp.ones_like(o)
                    for o, og in zip(outs, out_grads))
                (grads,) = vjp(cts)
                return outs, grads, new_aux

            self._bwd_jit = jax.jit(run)
            self._bwd_wrt_idx = wrt_idx
        return self._bwd_jit

    def _store_grad(self, tgt, g, req):
        """Write a gradient back honoring the grad array's OWN device
        (group2ctx grads live with their parameters)."""
        g = g.astype(tgt.dtype)
        if tgt.context.jax_device != self._ctx.jax_device:
            g = jax.device_put(g, tgt.context.jax_device)
        tgt._data = (tgt._data + g) if req == "add" else g

    def _gather_args(self, arrays):
        """Array values for the jitted program, streaming any that reside
        on another device (group2ctx parameter placement) onto the compute
        ctx — one program, per-step transfers at the group boundary."""
        dev = self._ctx.jax_device
        out = []
        for a in arrays:
            v = a._data
            if hasattr(v, "devices") and v.devices() != {dev}:
                v = jax.device_put(v, dev)
            out.append(v)
        return tuple(out)

    # -- API -----------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Run forward (reference `executor.py:114 forward` → `MXExecutorForward`)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"Unknown argument {k}")
            tgt = self.arg_dict[k]
            src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            if src.dtype != tgt.dtype:
                src = src.astype(tgt.dtype)
            tgt._data = jax.device_put(src, self._ctx.jax_device)
        from . import random as _random
        key = _random.next_key() if self._n_rng else jax.random.PRNGKey(0)
        self._last_key = key
        self._last_is_train = is_train
        args = self._gather_args(self.arg_arrays)
        aux = self._gather_args(self.aux_arrays)
        self._exec_count = getattr(self, "_exec_count", 0) + 1
        trains = bool(is_train) and any(
            r != "null" for r in self._grad_req.values())
        if trains:
            # stash the vjp residuals: backward() replays ONLY the
            # linearized backward pass (no second forward)
            fwd = self._forward_res_jit()
            outs, new_aux, self._stashed_vjp = fwd(args, aux, key)
        else:
            self._stashed_vjp = None
            fwd = self._forward_jit(bool(is_train))
            outs, new_aux = fwd(args, aux, key)
        if is_train:
            for a, v in zip(self.aux_arrays, new_aux):
                a._data = v
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, arr in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, arr)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Run backward (reference `graph_executor.cc:76 Backward`).  When
        `forward(is_train=True)` stashed vjp residuals, ONLY the
        linearized backward program runs (the reference reuses forward
        activations identically); without a prior training forward it
        falls back to the combined forward+vjp program with the stashed
        rng key."""
        n_out = len(self._symbol._entries)
        if out_grads is None:
            ogs = tuple([None] * n_out)
        elif isinstance(out_grads, NDArray):
            ogs = (out_grads._data,) + tuple([None] * (n_out - 1))
        else:
            ogs = tuple(g._data if isinstance(g, NDArray) else g
                        for g in out_grads)
        stashed = getattr(self, "_stashed_vjp", None)
        if stashed is not None:
            # cotangent defaults come from the LIVE outputs (no eval_shape
            # re-trace needed)
            ogs = tuple(
                jnp.ones(o._data.shape, o._data.dtype) if g is None else g
                for g, o in zip(ogs, self.outputs))
            self._exec_count = getattr(self, "_exec_count", 0) + 1
            grads = self._vjp_apply_jit(stashed, ogs)
            # residuals pin the forward activations in device memory —
            # release them now that they are consumed (a repeated bare
            # backward() falls back to the combined program)
            self._stashed_vjp = None
        else:
            run = self._backward_jit()
            args = self._gather_args(self.arg_arrays)
            aux = self._gather_args(self.aux_arrays)
            key = self._last_key if self._last_key is not None \
                else jax.random.PRNGKey(0)
            if any(g is None for g in ogs):
                # cheap eval_shape once per signature for output shapes
                fwd = self._forward_jit(True)
                outs, _ = jax.eval_shape(fwd, args, aux, key)
                ogs = tuple(jnp.ones(o.shape, o.dtype) if g is None else g
                            for g, o in zip(ogs, outs))
            self._exec_count = getattr(self, "_exec_count", 0) + 1
            outs, grads, new_aux = run(args, aux, key, ogs)
        arg_names = self._symbol.list_arguments()
        for i, g in zip(self._bwd_wrt_idx, grads):
            tgt = self.grad_arrays[i]
            if tgt is None:
                continue
            self._store_grad(tgt, g, self._grad_req.get(arg_names[i]))
        return [NDArray(g, ctx=self._ctx) for g in grads]

    def forward_backward(self, out_grads=None, **kwargs):
        """Fused train step (one XLA program; used by Module for performance)."""
        for k, v in kwargs.items():
            tgt = self.arg_dict[k]
            src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            if src.dtype != tgt.dtype:
                src = src.astype(tgt.dtype)
            tgt._data = jax.device_put(src, self._ctx.jax_device)
        from . import random as _random
        key = _random.next_key() if self._n_rng else jax.random.PRNGKey(0)
        self._last_key = key
        # a residual stash from an earlier forward(is_train=True) is now
        # stale; a later bare backward() must fall back to the combined
        # program, not linearize at the OLD inputs
        self._stashed_vjp = None
        run = self._backward_jit()
        args = self._gather_args(self.arg_arrays)
        aux = self._gather_args(self.aux_arrays)
        n_out = len(self._symbol._entries)
        fwd = self._forward_jit(True)
        outs_s, _ = jax.eval_shape(fwd, args, aux, key)
        ogs = tuple(jnp.ones(o.shape, o.dtype) for o in outs_s)
        if out_grads is not None:
            ogs = tuple(g._data if g is not None else d
                        for g, d in zip(out_grads, ogs))
        outs, grads, new_aux = run(args, aux, key, ogs)
        for a, v in zip(self.aux_arrays, new_aux):
            a._data = v
        arg_names = self._symbol.list_arguments()
        for i, g in zip(self._bwd_wrt_idx, grads):
            tgt = self.grad_arrays[i]
            if tgt is None:
                continue
            self._store_grad(tgt, g, self._grad_req.get(arg_names[i]))
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Reference `executor.py copy_params_from`.  Each array keeps ITS
        OWN context: group2ctx-placed parameters stay on their group's
        device (that residency is the point of the feature).  All values
        move in ONE batched transfer — per-param round trips dominate on a
        remote chip."""
        plan = []   # (target NDArray, host/src value)

        def gather(params, table, what):
            for k, v in params.items():
                if k in table:
                    tgt = table[k]
                    src = v._data if isinstance(v, NDArray) else v
                    if hasattr(src, "astype") and src.dtype != tgt.dtype:
                        src = src.astype(tgt.dtype)
                    plan.append((tgt, src))
                elif not allow_extra_params:
                    raise MXNetError(f"Found name {k} not in {what}")

        gather(arg_params, self.arg_dict, "arguments")
        if aux_params:
            gather(aux_params, self.aux_dict, "aux states")
        if plan:
            moved = jax.device_put(
                [_np.asarray(s) if isinstance(s, (list, tuple)) else s
                 for _, s in plan],
                [t.context.jax_device for t, _ in plan])
            for (tgt, _), v in zip(plan, moved):
                tgt._data = v

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (reference `executor.py reshape`); jit
        re-specializes per signature so this only reallocates buffers."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        new_args = []
        new_grads = []
        for name, sh, old, g in zip(arg_names, arg_shapes, self.arg_arrays,
                                    self.grad_arrays):
            if sh != old.shape:
                new_args.append(NDArray(jnp.zeros(sh, old.dtype), ctx=self._ctx))
                new_grads.append(None if g is None else
                                 NDArray(jnp.zeros(sh, old.dtype), ctx=self._ctx))
            else:
                new_args.append(old)
                new_grads.append(g)
        new_aux = []
        for sh, old in zip(aux_shapes, self.aux_arrays):
            new_aux.append(old if sh == old.shape else
                           NDArray(jnp.zeros(sh, old.dtype), ctx=self._ctx))
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Reference `MXExecutorSetMonitorCallback` (per-output monitoring)."""
        self._monitor_callback = callback

    def debug_str(self):
        lines = [f"Symbol outputs: {self._symbol.list_outputs()}"]
        for n in self._symbol._topo():
            kind = "var" if n.is_variable else n.op.name
            lines.append(f"  {kind} {n.name}")
        return "\n".join(lines)

    # -- construction --------------------------------------------------------
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, shape_kwargs,
                     group2ctx=None):
        from .symbol.symbol import check_unique_names
        check_unique_names(symbol)  # shadowed names would train wrong arrays
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape_kwargs)
        if arg_shapes is None:
            raise MXNetError("simple_bind: shape inference failed")
        type_dict = type_dict or {}

        # reference group2ctx (`graph_executor.cc` ctx assignment from
        # __ctx_group__ attrs): parameter arrays RESIDE on their group's
        # device — the memory-placement half of legacy model parallelism.
        # Compute still runs as one XLA program on the bound ctx (inputs
        # stream in per step); per-group COMPUTE placement is the job of
        # the sharding layer (`parallel.group2ctx_shardings` bridges this
        # API to mesh shardings for true SPMD model parallel).
        var_group = {}
        if group2ctx:
            for node in symbol._topo():
                if node.is_variable:
                    g = node._extra_attrs.get("__ctx_group__")
                    if g is not None and g in group2ctx:
                        var_group[node.name] = group2ctx[g]

        # allocate every array in ONE batched transfer: per-array
        # device_put costs a host<->device round trip each — ~300 arrays
        # over a remote-chip link dominates bind time otherwise
        plan = []   # (host_buffer, device) in creation order

        def make(shape, name):
            dt = np_dtype(type_dict.get(name, _np.float32))
            dev_ctx = var_group.get(name, ctx)
            plan.append((_np.zeros(shape, dt), dev_ctx.jax_device))
            return dev_ctx

        arg_ctxs = [make(s, n) for n, s in zip(arg_names, arg_shapes)]
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = {n: grad_req.get(n, "null") for n in arg_names}
        grad_ctxs = [make(s, n) if reqs.get(n, "null") != "null" else None
                     for n, s in zip(arg_names, arg_shapes)]
        aux_ctxs = [make(s, n) for n, s in zip(aux_names, aux_shapes)]

        bufs = jax.device_put([b for b, _ in plan], [d for _, d in plan])
        it = iter(bufs)
        args = [NDArray(next(it), ctx=c) for c in arg_ctxs]
        grads = [NDArray(next(it), ctx=c) if c is not None else None
                 for c in grad_ctxs]
        auxs = [NDArray(next(it), ctx=c) for c in aux_ctxs]
        return Executor(symbol, ctx, args, grads, reqs, auxs)

    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states):
        from .symbol.symbol import check_unique_names
        check_unique_names(symbol)  # shadowed names would train wrong arrays
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        ctx = ctx or current_context()

        def to_list(d, names, what):
            if d is None:
                return [None] * len(names)
            if isinstance(d, dict):
                return [d.get(n) for n in names]
            if len(d) != len(names):
                raise MXNetError(f"Length of {what} does not match number of "
                                 f"{what} names")
            return list(d)

        arg_arrays = to_list(args, arg_names, "arguments")
        missing = [n for n, a in zip(arg_names, arg_arrays) if a is None]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")
        grad_arrays = to_list(args_grad, arg_names, "gradients")
        aux_arrays = to_list(aux_states, aux_names, "aux states")
        aux_arrays = [a if a is not None else
                      NDArray(jnp.zeros((1,), _np.float32), ctx=ctx)
                      for a in aux_arrays]
        if args_grad is None:
            grad_req = "null"
            grad_arrays = [None] * len(arg_names)
        return Executor(symbol, ctx, arg_arrays, grad_arrays, grad_req,
                        aux_arrays)
