"""JobSupervisor: the per-host control plane for elastic multi-host runs.

On a real TPU pod one lost host hangs every surviving host inside an XLA
collective forever — no error, no timeout, no restart path.  The
supervisor turns that silent hang into a detected event with a
deterministic recovery:

* **heartbeat/membership** — a background thread heartbeats the
  coordinator (the root parameter server, `dist/membership.py`) over its
  OWN sequence-numbered transport channel every ``heartbeat_s``; each
  reply carries the pod view (alive/dead hosts, per-host step counters
  and step-time EWMAs, the membership epoch).  Epochs are fenced: a host
  that missed a shrink gets a stale-epoch rejection and must die, not
  rejoin.

* **hung-collective watchdog** — `collective(name, fn)` runs a blocking
  cross-host exchange (kvstore push/pull/barrier, a dispatched all-reduce)
  on a worker thread under a deadline.  On expiry it raises a structured
  `CollectiveTimeoutError` naming the collective, the mesh axis, and the
  hosts that failed to arrive (dead or step-lagging, from membership
  data) instead of blocking forever.

* **straggler detection** — `record_step` maintains this host's step-time
  EWMA (shipped with heartbeats); every view is scanned for hosts whose
  EWMA diverges more than ``straggler_k``·sigma from the pod median, and a
  finding lands in `analysis.runtime_report()` plus the profiler trace.

* **shrink-and-resume** — on confirmed host loss, `shrink()` drives the
  epoch-fenced barrier-with-deadline on the coordinator: survivors agree
  on the new world size, get densely re-ranked, the server resets kvstore
  state for the new epoch, and `Module.fit(checkpoint_dir=...)` restarts
  from the last committed checkpoint at the smaller world size.

Fault sites (`MXNET_FAULTS`): ``heartbeat.send`` (a ``drop`` skips the
beat — lossy control network), ``collective.dispatch`` (a ``hang`` sleeps
inside the dispatched collective — the lost-host stall, deterministically)
and ``host.step`` in the fit loop (a ``kill`` is a whole-host SIGKILL).
"""
from __future__ import annotations

import os
import threading
import time

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..base import MXNetError
from . import faults as _faults

__all__ = ["JobSupervisor", "CollectiveTimeoutError", "HostLostError",
           "StaleEpochError", "ShrinkResult", "current", "activate",
           "deactivate", "supervised", "findings", "reset_findings"]


class CollectiveTimeoutError(MXNetError):
    """A cross-host collective did not complete within the watchdog
    deadline.  Structured: `collective` (name), `axis` (mesh axis),
    `timeout_s`, `absent` (ranks that failed to arrive, from membership
    data), `epoch` (membership epoch).  `Module.fit` with a
    ``checkpoint_dir`` converts this into shrink-and-resume."""

    def __init__(self, collective, axis=None, timeout_s=0.0, absent=(),
                 detail="", epoch=0):
        self.collective = str(collective)
        self.axis = axis
        self.timeout_s = float(timeout_s)
        self.absent = sorted(int(r) for r in absent)
        self.epoch = int(epoch)
        where = f" over axis {axis!r}" if axis else ""
        if self.absent:
            who = (f"; host(s) {self.absent} failed to arrive"
                   + (f" ({detail})" if detail else ""))
        else:
            who = (f"; {detail}" if detail else
                   "; every member still heartbeats — the collective "
                   "itself is wedged or the deadline is too tight")
        super().__init__(
            f"collective {self.collective!r}{where} timed out after "
            f"{self.timeout_s:g}s at membership epoch {self.epoch}{who} — "
            "shrink the pod and resume from the last checkpoint "
            "(Module.fit(checkpoint_dir=...) does this automatically)")


class HostLostError(MXNetError):
    """Membership confirmed one or more hosts dead (heartbeat deadline
    passed).  `ranks` names them; `epoch` is the membership epoch."""

    def __init__(self, ranks, epoch=0, detail=""):
        self.ranks = sorted(int(r) for r in ranks)
        self.epoch = int(epoch)
        super().__init__(
            f"host(s) {self.ranks} lost at membership epoch {self.epoch}"
            + (f": {detail}" if detail else "")
            + " — survivors must shrink and resume from the last "
              "checkpoint")


class StaleEpochError(MXNetError):
    """This host carries a stale membership epoch (it missed a shrink and
    is fenced out).  It must exit, not retry."""


class ShrinkResult:
    """Outcome of one committed shrink, from this host's point of view."""

    __slots__ = ("epoch", "world_size", "rank", "survivors", "rank_map")

    def __init__(self, epoch, world_size, rank, survivors, rank_map):
        self.epoch = int(epoch)
        self.world_size = int(world_size)
        self.rank = int(rank)              # this host's NEW rank
        self.survivors = list(survivors)   # OLD ranks, sorted
        self.rank_map = dict(rank_map)     # old rank -> new rank

    def __repr__(self):
        return (f"ShrinkResult(epoch={self.epoch}, "
                f"world_size={self.world_size}, rank={self.rank}, "
                f"survivors={self.survivors})")


# -- the active supervisor (one per process) ----------------------------------
_current = [None]
_lock = _locks.make_lock("supervisor.findings")
_findings = []          # straggler / host-loss findings for runtime_report


def current():
    """The process's active JobSupervisor, or None."""
    return _current[0]


def activate(sup):
    """Install `sup` as the process's active supervisor: collective call
    sites (`dist.kvstore_dist`, `parallel.collectives.supervised`) route
    through its watchdog while one is active."""
    _current[0] = sup


def deactivate(sup=None):
    """Remove the active supervisor (only `sup` when given, so a stale
    deactivate cannot evict a newer supervisor)."""
    if sup is None or _current[0] is sup:
        _current[0] = None


def supervised(name, fn, axis=None, timeout=None):
    """Run a blocking cross-host collective under the active supervisor's
    watchdog; a plain call when none is active."""
    sup = current()
    if sup is None:
        return fn()
    return sup.collective(name, fn, axis=axis, timeout=timeout)


def findings():
    """Supervisor findings (stragglers, host losses) for
    `analysis.runtime_report()`."""
    with _lock:
        return list(_findings)


def reset_findings():
    with _lock:
        _findings.clear()


def _add_finding(code, message, key):
    """Deduplicate by (code, key): repeats bump the count."""
    from ..analysis.findings import Finding, WARN
    with _lock:
        for f in _findings:
            if f.code == code and f.node == key:
                f.count += 1
                return
        _findings.append(Finding("supervisor." + code.split("-")[0], code,
                                 WARN, message, node=key))


class _Dispatcher:
    """One persistent worker thread executing watchdogged collectives in
    submission order.  A training step dispatches several collectives
    (push, pull, barrier) — a thread per call would put thread creation
    on the hot path; one long-lived worker amortizes it.  When a call
    times out, the worker is wedged inside it by definition: the
    supervisor abandons this dispatcher (thread and all) and builds a
    fresh one for the next collective."""

    def __init__(self, name):
        import queue
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._thread.start()

    def _loop(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            fn, box, done = job
            try:
                box["value"] = fn()
            except BaseException as exc:   # noqa: BLE001 — relayed
                box["error"] = exc
            finally:
                done.set()

    def submit(self, fn):
        box = {"value": None, "error": None}
        done = threading.Event()
        self._q.put((fn, box, done))
        return box, done

    def close(self):
        self._q.put(None)


class JobSupervisor:
    """Per-host supervisor: heartbeats, watchdog, stragglers, shrink."""

    def __init__(self, rank, num_workers, host=None, port=None, epoch=None,
                 heartbeat_s=None, deadline_s=None, collective_timeout_s=None,
                 straggler_k=None, shrink_barrier_s=None,
                 clock=time.monotonic):
        from .. import config as _config
        self.rank = int(rank)
        self.num_workers = int(num_workers)
        self.host = host or os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self.port = int(port if port is not None
                        else os.environ.get("DMLC_PS_ROOT_PORT", 9091))
        self.epoch = int(epoch if epoch is not None
                         else _config.get("MXNET_SUPERVISOR_EPOCH"))
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else _config.get("MXNET_SUPERVISOR_HEARTBEAT_S"))
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else _config.get("MXNET_SUPERVISOR_DEADLINE_S"))
        self.collective_timeout_s = float(
            collective_timeout_s if collective_timeout_s is not None
            else _config.get("MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S"))
        self.straggler_k = float(
            straggler_k if straggler_k is not None
            else _config.get("MXNET_SUPERVISOR_STRAGGLER_K"))
        self.shrink_barrier_s = float(
            shrink_barrier_s if shrink_barrier_s is not None
            else _config.get("MXNET_SUPERVISOR_SHRINK_BARRIER_S"))
        self._clock = clock
        self._chan = None
        self._thread = None
        self._dispatcher = None
        self._stop = threading.Event()
        self._view_lock = _locks.make_lock("supervisor.view")
        self._view = None
        self._fenced = False
        self._kvstore = None
        self._step = 0
        self._ewma = None
        self._dead_seen = {}      # rank -> monotonic time first seen dead
        self._stragglers = set()  # ranks already flagged
        # counters shared between the heartbeat thread and the fit
        # thread: every update holds _view_lock (mxtsan flagged the
        # bare `+= 1` pattern as write/write races between the beat
        # loop and the collective/watchdog path)
        self._stats = _tsan.shared_dict(
            f"supervisor.stats[rank{self.rank}]",
            {"heartbeats": 0, "heartbeats_dropped": 0,
             "heartbeats_failed": 0, "collectives": 0,
             "collective_timeouts": 0, "stragglers_flagged": 0,
             "hosts_lost": 0})
        _tsan.instrument(self, f"supervisor[rank{self.rank}]")
        # telemetry plane: heartbeat/watchdog/straggler counters under
        # the stable 'supervisor' namespace (weakly held)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("supervisor", self.stats)

    @classmethod
    def for_kvstore(cls, kv, **kw):
        """Build a supervisor from a dist kvstore's identity (rank, world
        size, root-server address) and attach its retry/breaker counters
        to `stats()`."""
        chan = getattr(kv, "_chan", None)
        sup = cls(rank=kv.rank, num_workers=kv.num_workers,
                  host=getattr(chan, "host", None),
                  port=getattr(chan, "port", None), **kw)
        sup.attach_kvstore(kv)
        return sup

    def attach_kvstore(self, kv):
        self._kvstore = kv

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        """Open the heartbeat channel (its OWN channel: a request blocked
        in a hung collective must not also silence the heartbeats), beat
        once synchronously so membership knows this host before the first
        interval, and start the beat loop."""
        from ..dist.transport import Channel
        self._chan = Channel(self.host, self.port,
                             timeout=max(self.deadline_s, 1.0))
        self._beat()
        self._thread = threading.Thread(target=self._beat_loop, daemon=True,
                                        name=f"mx-supervisor-hb-{self.rank}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._dispatcher is not None:
            self._dispatcher.close()
            self._dispatcher = None
        if self._thread is not None:
            _tsan.join_thread(self._thread,
                              max(self.deadline_s, 1.0) + 1.0,
                              owner=f"JobSupervisor[rank{self.rank}]")
            self._thread = None
        if self._chan is not None:
            try:
                self._chan.close()
            except Exception:
                pass
            self._chan = None

    def _beat_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            if self._fenced:
                return
            self._beat()

    def _beat(self):
        try:
            _faults.fire("heartbeat.send", rank=self.rank)
        except Exception:
            # an injected (or genuinely lossy) dropped heartbeat: skip
            # this beat — the deadline tolerates deadline_s/heartbeat_s
            # consecutive losses before declaring death
            with self._view_lock:
                self._stats["heartbeats_dropped"] += 1
            return
        msg = {"cmd": "hb", "rank": self.rank, "epoch": self.epoch,
               "step": self._step, "step_time": self._ewma}
        try:
            reply = self._chan.request(msg)
        except Exception:
            with self._view_lock:
                self._stats["heartbeats_failed"] += 1
            return
        with self._view_lock:
            self._stats["heartbeats"] += 1
        err = reply.get("error") if isinstance(reply, dict) else None
        if err is not None:
            if "stale epoch" in err:
                with self._view_lock:
                    self._fenced = True
                _faults.note("fenced", site="supervisor", rank=self.rank,
                             epoch=self.epoch)
            return
        view = reply.get("view")
        if view is not None:
            self._on_view(view)

    # -- membership view ------------------------------------------------------
    def _on_view(self, view):
        now = self._clock()
        with self._view_lock:
            self._view = view
        for r in view.get("dead", ()):
            if r == self.rank or r in self._dead_seen:
                continue
            self._dead_seen[r] = now
            with self._view_lock:
                self._stats["hosts_lost"] += 1
            age = view.get("age", {}).get(r)
            _add_finding(
                "host-lost",
                f"host rank {r} stopped heartbeating "
                f"({age if age is not None else '?'}s silent, deadline "
                f"{self.deadline_s:g}s) at membership epoch "
                f"{view.get('epoch', self.epoch)}", f"rank{r}")
            _faults.note("host-dead", site="supervisor", rank=r,
                         observer=self.rank)
            try:
                from .. import profiler as _profiler
                _profiler.record_supervisor("host-lost", rank=r,
                                            observer=self.rank)
            except Exception:
                pass
        self._check_stragglers(view)

    def _check_stragglers(self, view):
        """Flag hosts whose step-time EWMA diverges > k*sigma from the pod
        median.  Both statistics EXCLUDE the candidate host: with the
        candidate included, a single straggler's deviation from the
        median is bounded at n/sqrt(n-1) sigma (its own EWMA inflates
        the population sigma), so k=3 would be mathematically
        unreachable on any pod under ~10 hosts no matter how slow the
        straggler.  A relative sigma floor (5% of the peers' median)
        keeps a near-uniform pod's vanishing sigma from flagging
        noise-level divergence."""
        ewma = {int(r): float(v) for r, v in (view.get("ewma") or {}).items()
                if v is not None}
        alive = set(view.get("alive", ()))
        pod = {r: v for r, v in ewma.items() if r in alive}
        if len(pod) < 2:
            return
        for r, v in sorted(pod.items()):
            if r in self._stragglers:
                continue
            peers = sorted(pv for pr, pv in pod.items() if pr != r)
            mid = peers[len(peers) // 2] if len(peers) % 2 else \
                0.5 * (peers[len(peers) // 2 - 1] + peers[len(peers) // 2])
            mean = sum(peers) / len(peers)
            sigma = (sum((p - mean) ** 2 for p in peers)
                     / len(peers)) ** 0.5
            if v - mid > self.straggler_k * max(sigma, 0.05 * mid) and \
                    v > 1.2 * mid:
                self._stragglers.add(r)
                with self._view_lock:
                    self._stats["stragglers_flagged"] += 1
                _add_finding(
                    "straggler-host",
                    f"host rank {r} step time {v * 1e3:.1f}ms diverges "
                    f">{self.straggler_k:g} sigma from the pod median "
                    f"{mid * 1e3:.1f}ms — a straggler throttles every "
                    "synchronous step to its pace (check its input "
                    "pipeline, thermal state, or neighbors)", f"rank{r}")
                try:
                    from .. import profiler as _profiler
                    _profiler.record_supervisor("straggler", rank=r,
                                                ewma_ms=v * 1e3,
                                                median_ms=mid * 1e3)
                except Exception:
                    pass

    def view(self):
        """The latest membership view (None before the first reply)."""
        with self._view_lock:
            return dict(self._view) if self._view is not None else None

    def dead_hosts(self):
        v = self.view() or {}
        return [r for r in v.get("dead", ()) if r != self.rank]

    def _absent_hosts(self):
        """Who a timed-out collective is waiting on: confirmed-dead hosts
        plus alive hosts whose step counter lags this host's (they never
        arrived at this round — the hung-but-alive case)."""
        v = self.view() or {}
        absent = {int(r) for r in v.get("dead", ()) if int(r) != self.rank}
        steps = v.get("steps") or {}
        for r, s in steps.items():
            r = int(r)
            if r != self.rank and r not in absent and int(s) < self._step:
                absent.add(r)
        detail = ", ".join(
            f"rank {r}: " + (f"silent {v.get('age', {}).get(r)}s"
                             if r in set(v.get("dead", ()))
                             else f"at step {steps.get(r)} vs {self._step}")
            for r in sorted(absent))
        return sorted(absent), detail

    # -- step accounting ------------------------------------------------------
    def record_step(self, seconds):
        """One training step's wall time: update the EWMA shipped with
        heartbeats and advance the step counter membership lag-detection
        keys on."""
        self._step += 1
        s = float(seconds)
        self._ewma = s if self._ewma is None else \
            0.8 * self._ewma + 0.2 * s

    # -- hung-collective watchdog --------------------------------------------
    def collective(self, name, fn, axis=None, timeout=None):
        """Run the blocking collective `fn` under the watchdog deadline.
        On expiry, raise `CollectiveTimeoutError` naming the collective,
        the axis, and the hosts that failed to arrive; the abandoned
        worker thread is left to die with its (doomed) socket or device
        wait — the caller's recovery path tears that transport down."""
        if self._fenced:
            raise StaleEpochError(
                f"host rank {self.rank} is fenced out at membership epoch "
                f"{self.epoch} (it missed a shrink); refusing to dispatch "
                f"collective {name!r} — exit and rejoin at the current "
                "epoch")
        deadline = float(timeout if timeout is not None
                         else self.collective_timeout_s)
        with self._view_lock:
            self._stats["collectives"] += 1

        def _run():
            _faults.fire("collective.dispatch", collective=name,
                         rank=self.rank)
            return fn()

        if self._dispatcher is None:
            self._dispatcher = _Dispatcher(
                f"mx-collective-worker-{self.rank}")
        box, done = self._dispatcher.submit(_run)
        if not done.wait(deadline):
            # the worker is wedged inside the hung collective: abandon
            # it (thread and all) — the next collective gets a fresh one
            self._dispatcher = None
            with self._view_lock:
                self._stats["collective_timeouts"] += 1
            absent, detail = self._absent_hosts()
            _faults.note("collective-timeout", site="supervisor",
                         collective=name, rank=self.rank,
                         timeout_s=deadline)
            try:
                from .. import profiler as _profiler
                _profiler.record_supervisor("collective-timeout",
                                            collective=name,
                                            timeout_s=deadline)
            except Exception:
                pass
            raise CollectiveTimeoutError(
                name, axis=axis, timeout_s=deadline, absent=absent,
                detail=detail, epoch=self.epoch)
        if box["error"] is not None:
            raise box["error"]
        return box["value"]

    # -- shrink-and-resume ----------------------------------------------------
    def shrink(self, reason=""):
        """Drive the epoch-fenced shrink barrier on the coordinator.
        Blocks until every still-alive host proposed (or the barrier
        deadline), then returns this host's `ShrinkResult`.  Uses a FRESH
        channel: the main control channel may be wedged in the very hang
        being recovered from."""
        from ..dist.transport import Channel
        # the coordinator's barrier waits up to max(barrier_s, watchdog +
        # 2*heartbeat deadline) for peers whose watchdogs fire later than
        # ours (dist/server.py) — the request timeout must cover that
        chan = Channel(self.host, self.port,
                       timeout=max(self.shrink_barrier_s,
                                   self.collective_timeout_s
                                   + 2 * self.deadline_s) + 30.0)
        try:
            reply = chan.request({"cmd": "shrink", "rank": self.rank,
                                  "epoch": self.epoch,
                                  "reason": str(reason)[:500]})
        finally:
            try:
                chan.close()
            except Exception:
                pass
        if "error" in reply:
            if "stale epoch" in reply["error"]:
                with self._view_lock:
                    self._fenced = True
                raise StaleEpochError(reply["error"])
            raise MXNetError(f"shrink failed: {reply['error']}")
        rank_map = {int(k): int(v) for k, v in reply["rank_map"].items()}
        if self.rank not in rank_map:
            raise StaleEpochError(
                f"host rank {self.rank} missed the shrink barrier for "
                f"epoch {reply['epoch']} (survivors: {reply['survivors']})"
                " — fenced out")
        result = ShrinkResult(reply["epoch"], reply["world_size"],
                              rank_map[self.rank], reply["survivors"],
                              rank_map)
        _faults.note("shrink", site="supervisor", old_rank=self.rank,
                     new_rank=result.rank, world_size=result.world_size,
                     epoch=result.epoch)
        return result

    # -- observability --------------------------------------------------------
    def stats(self):
        """One dict of everything the supervisor (and the attached dist
        kvstore's PR 5 retry/breaker machinery) counted — exported into
        the `run_tpu_parity` / chaos artifacts."""
        v = self.view() or {}
        out = {
            "rank": self.rank,
            "epoch": self.epoch,
            "world_size": self.num_workers,
            "fenced": self._fenced,
            "step": self._step,
            "step_time_ewma_s": self._ewma,
            "alive": list(v.get("alive", ())),
            "dead": list(v.get("dead", ())),
        }
        with self._view_lock:
            out.update(self._stats)
        kv = self._kvstore
        if kv is not None and hasattr(kv, "stats"):
            try:
                out["kvstore"] = kv.stats()
            except Exception:
                pass
        return out
