"""Resilience: deterministic fault injection + retry/failover primitives.

Production-scale TPU training and serving die on the first unhandled
transient — worker preemption, dead parameter servers, slow peers,
half-written checkpoints are ROUTINE at pod scale, and none of them is
testable unless failures can be made deterministic.  This package is
both halves of that contract:

* **fault injection** (`faults`) — seeded, reproducible faults at named
  sites in the dist transport, parameter server, serving batcher, and
  checkpoint writer, driven by the ``MXNET_FAULTS`` env spec or
  `inject()`; every fired fault lands in a trace (`trace()`) so tests
  assert exact sequences;
* **failure handling** (`retry`, `breaker`) — `RetryPolicy`
  (exponential backoff + jitter, per-attempt and overall deadlines,
  retry budget), `CircuitBreaker` (consecutive-failure trip, half-open
  probes), and the structured `ServerLostError` raised when a parameter
  server is diagnosed permanently dead — the signal
  ``Module.fit(checkpoint_dir=..., resume=True)`` turns into an
  automatic restart from the last checkpoint.
* **elastic supervision** (`supervisor`) — the per-host `JobSupervisor`
  for multi-host runs: heartbeat/membership with fenced epochs, the
  hung-collective watchdog (`CollectiveTimeoutError` names the absent
  hosts instead of blocking forever), straggler detection, and the
  shrink-and-resume barrier `Module.fit` drives after a confirmed host
  loss.

With ``MXNET_FAULTS`` unset, every site hook is a function call behind
one global read — no locks, no syscalls, no behavior change.
"""
from __future__ import annotations

from ..base import MXNetError
from . import faults
from .faults import (FaultInjected, TornWrite, configure, inject, clear,
                     reset, trace, fire, active)
from .retry import RetryPolicy, RetryBudget
from .breaker import CircuitBreaker
from . import supervisor
from .supervisor import (JobSupervisor, CollectiveTimeoutError,
                         HostLostError, StaleEpochError)
from . import guardian
from .guardian import (TrainingGuardian, TrainingDivergedError,
                       RollbackRequested, QuarantineLog)

__all__ = ["faults", "FaultInjected", "TornWrite", "configure", "inject",
           "clear", "reset", "trace", "fire", "active", "RetryPolicy",
           "RetryBudget", "CircuitBreaker", "ServerLostError", "supervisor",
           "JobSupervisor", "CollectiveTimeoutError", "HostLostError",
           "StaleEpochError", "guardian", "TrainingGuardian",
           "TrainingDivergedError", "RollbackRequested", "QuarantineLog"]


class ServerLostError(MXNetError):
    """A parameter server is permanently gone (crashed, partitioned past
    the retry budget, or restarted empty).  Structured so training glue
    can act on it: `server` (index), `addr` ("host:port"), `keys` (the
    keys whose ranges that server owned).  `Module.fit` with a
    ``checkpoint_dir`` catches this and restarts from the last
    checkpoint instead of dying."""

    def __init__(self, server, addr, keys=(), reason=""):
        self.server = int(server)
        self.addr = str(addr)
        self.keys = sorted(str(k) for k in keys)
        shown = ", ".join(self.keys[:8])
        if len(self.keys) > 8:
            shown += f", ... ({len(self.keys)} keys)"
        super().__init__(
            f"parameter server {server} ({addr}) is lost"
            + (f": {reason}" if reason else "")
            + (f"; it owned key range(s) of [{shown}]" if self.keys else "")
            + " — restart the server and resume from the latest checkpoint "
              "(Module.fit(checkpoint_dir=..., resume=True) does this "
              "automatically)")
