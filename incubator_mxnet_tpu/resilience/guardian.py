"""Training guardian: in-graph numerical-health monitoring with a
skip / rollback / quarantine policy ladder.

Infra faults are covered elsewhere (fault injection + retry, host loss,
replica loss); this module defends the training loop against the
*silent* failures — a NaN gradient, a loss spike, a corrupt input
record — that either crash `Module.fit` mid-epoch or quietly poison the
parameters that checkpointing then faithfully preserves.

Three layers:

* **in-graph health word** — the fused train step (fused.py), when a
  guardian is attached, computes an all-finite reduction over the
  step's gradients, floating outputs and applied update, plus the
  per-step parameter-displacement ratio ||new_w - w|| / ||w|| (the
  training signal the spike detector watches) INSIDE the compiled
  program, and conditionally applies the update: a non-finite step's
  weight / optimizer-state / aux / metric updates are `where`-selected
  away (**skip-batch**) while the RNG key and update counts advance
  unconditionally, so a skipped step is deterministic and reproducible.
  The health word is returned as two device scalars per step — the host
  does NOT block on them; `maybe_poll` materializes the accumulated
  tokens every ``MXNET_GUARDIAN_INTERVAL`` steps (one gather), so
  steady-state overhead is a fused reduction per step and one small
  device->host read per interval (<2%, gated in bench.py).

* **policy ladder** (this module) — on each poll:

  - a **non-finite step** (already skipped in-graph) is counted,
    quarantined by stream position, and reported
    (`analysis.runtime_report()` + profiler + faults JSONL);
  - a **loss spike** — log(signal) above ``MXNET_GUARDIAN_SPIKE_K``
    EW standard deviations (sigma banded to [0.25, 1.25] log units)
    over the log-space EWMA after a ``MXNET_GUARDIAN_SPIKE_WINDOW``-step
    warmup, AND past the absolute displacement gate (the step moved the
    parameters by a damaging fraction of their norm — a lone relative
    outlier whose absolute displacement is harmless is a hard batch,
    not divergence) — already *applied* its damage, so the guardian
    requests **rollback-to-last-good**:
    `Module.fit` restores the newest checkpoint whose manifest carries
    a healthy ``health`` stamp at a step at or before the last in-bounds
    signal, replays the intervening good batches bit-identically
    (full-state restore: optimizer slots, update counts, RNG streams,
    iterator position), and skips the quarantined spike window;
  - **consecutive failures** past ``MXNET_GUARDIAN_MAX_FAILURES`` (or
    rollbacks past ``MXNET_GUARDIAN_MAX_ROLLBACKS``) escalate to a
    structured `TrainingDivergedError` naming the step, the signal
    value, and the offending data shard.

* **bad-data quarantine** — every skipped / rolled-back position (and
  every corrupt record the io layer detects) is appended as one JSON
  line to a quarantine file (``<checkpoint_dir>/quarantine.jsonl`` by
  default); a resumed run loads it and skips the same positions, so a
  poisonous batch is consumed exactly zero times after diagnosis.

Multi-worker: health bits are all-reduced through the kvstore (inside
the supervisor's watchdog fence when one is active) so every worker
takes the same skip/rollback decision; a worker whose local shard
produced the bad batch propagates its verdict to workers that saw a
clean step.  Degrades to local decisions (with a counted warning) when
the store cannot reduce.

Fault sites: ``grad.nonfinite`` (an ``error`` clause poisons that
step's gradients with NaN in-graph), ``loss.spike`` (scales the step's
gradients by 1e6 — a detectable, damaging spike), ``io.corrupt_record`` (the
`faults.mutate` payload hook; a ``corrupt`` clause bit-flips record
bytes) — all deterministic, exercised end-to-end by
``tools/run_chaos.py --train``.
"""
from __future__ import annotations

import json
import math
import os
import time

import numpy as _np

from ..analysis import locks as _locks
from ..base import MXNetError
from . import faults as _faults

__all__ = ["TrainingGuardian", "TrainingDivergedError", "RollbackRequested",
           "QuarantineLog", "findings", "reset_findings"]

_SPIKE_SCALE = 1.0e6   # gradient multiplier an injected loss.spike applies
_LOG_FLOOR = 1.0e-30   # signal floor before taking logs (all-zero grads)
# log-space sigma band bounds: the detector works on log(signal), where
# training's exponential decay is a drift the EW variance absorbs.  The
# lower bound keeps a near-constant signal's vanishing sigma from
# flagging noise (k*0.25 ~ a 4.5x jump at k=6); the upper bound keeps a
# fast-decaying warmup's huge variance from hiding real spikes (k*1.25
# ~ a 1800x jump at k=6 — the injected scale clears it with headroom).
_SIGMA_LO, _SIGMA_HI = 0.25, 1.25
# absolute spike gate on the signal itself: the signal is the
# parameter-DISPLACEMENT ratio ||new_w - w|| / ||w|| per step, so a
# spike must ALSO have moved the parameters by a damaging fraction of
# their norm.  A converged model's gradient noise spans decades — a
# relative jump whose absolute displacement is harmless (1e-5 of the
# weights) is a hard batch, never a rollback.
_SPIKE_MIN_DISPLACEMENT = 0.25


class TrainingDivergedError(MXNetError):
    """Training health is unrecoverable by the guardian's ladder: too
    many consecutive non-finite/spiking steps (or too many rollbacks).
    Structured: `step`, `signal` (the gradient-norm training signal at
    the failing step, NaN for a non-finite step), `shard` (offending
    data source/range when the iterator could attribute it), `reason`.
    """

    def __init__(self, step, signal=None, shard=None, reason=""):
        self.step = int(step)
        self.signal = None if signal is None else float(signal)
        self.shard = shard
        sig = "non-finite" if self.signal is None or \
            not math.isfinite(self.signal) else f"{self.signal:.6g}"
        where = f" (offending data: {shard})" if shard else ""
        super().__init__(
            f"training diverged at step {self.step}: health signal "
            f"{sig}{where}"
            + (f" — {reason}" if reason else "")
            + "; the guardian's skip/rollback budget is exhausted — "
              "inspect the quarantine log, the data shard, and the "
              "learning-rate schedule before resuming")


class RollbackRequested(MXNetError):
    """Internal control-flow signal: the guardian diagnosed a loss spike
    whose update was already applied and wants `Module.fit` to restore
    the newest healthy checkpoint at or before `last_good_step` and skip
    the quarantined window.  Caught by the fit restart loop — user code
    only ever sees `TrainingDivergedError` when the budget runs out."""

    def __init__(self, step, last_good_step, signal, quarantined=()):
        self.step = int(step)
        self.last_good_step = int(last_good_step)
        self.signal = float(signal)
        self.quarantined = list(quarantined)
        super().__init__(
            f"loss spike at step {self.step} (signal {self.signal:.6g}); "
            f"rolling back to the newest healthy checkpoint at step <= "
            f"{self.last_good_step} and skipping "
            f"{len(self.quarantined)} quarantined batch position(s)")


# -- findings (analysis.runtime_report) ---------------------------------------
_lock = _locks.make_lock("guardian.findings")
_findings = []


def findings():
    """Guardian findings (skips, rollbacks, quarantines, divergence) for
    `analysis.runtime_report()`."""
    with _lock:
        return list(_findings)


def reset_findings():
    with _lock:
        _findings.clear()


def _add_finding(code, message, key, severity=None):
    from ..analysis.findings import Finding, WARN
    with _lock:
        for f in _findings:
            if f.code == code and f.node == key:
                f.count += 1
                return
        _findings.append(Finding("guardian." + code.split("-")[0], code,
                                 severity or WARN, message, node=key))


def _record_event(event, **args):
    """One guardian event into every observability plane: the faults
    JSONL trace (chaos artifacts), the profiler (step-aligned chrome
    trace with a thread lane), and the findings list."""
    _faults.note(event, site="guardian", **args)
    try:
        from .. import profiler as _profiler
        _profiler.record_guardian(event, **args)
    except Exception:
        pass


class QuarantineLog:
    """Append-only JSONL quarantine file shared by every process of a
    run — written through the one tested sink (`obs.jsonl_sink`:
    O_APPEND line-atomic appends, pid/rank/thread stamping).  Each
    entry is one poisoned unit: a batch position ({'epoch','nbatch'})
    or a record ({'source','record'})."""

    def __init__(self, path):
        from ..obs import jsonl_sink as _jsonl
        self.path = str(path)
        self._jsonl = _jsonl
        self._sink = _jsonl.sink(self.path)

    def append(self, **entry):
        self._sink.write(entry)

    def load(self):
        """Every entry written so far (any process), oldest first."""
        return self._jsonl.read_jsonl(self.path)

    def batch_positions(self):
        """{(epoch, nbatch)} of every quarantined stream position."""
        return {(int(e["epoch"]), int(e["nbatch"])) for e in self.load()
                if "nbatch" in e and "epoch" in e}

    def records(self, source=None):
        """{record_id} quarantined for `source` (or any source)."""
        return {int(e["record"]) for e in self.load()
                if "record" in e and
                (source is None or e.get("source") == source)}

    def close(self):
        self._sink.close()


class TrainingGuardian:
    """Per-fit training health guardian (see module docstring).

    Lifecycle: `Module.fit` builds one per fit() call
    (`TrainingGuardian.maybe_create`), `attach()`es it to the bound
    module after `init_optimizer` (wires the fused step's in-graph
    health word, the kvstore reduction, and the iterator's quarantine),
    then calls `tag()` + `maybe_poll()` per processed block and
    `health_stamp()` at every checkpoint snapshot."""

    @classmethod
    def maybe_create(cls, checkpoint_dir=None, logger=None):
        from .. import config as _config
        if not _config.get("MXNET_GUARDIAN"):
            return None
        return cls(checkpoint_dir=checkpoint_dir, logger=logger)

    def __init__(self, checkpoint_dir=None, interval=None, window=None,
                 spike_k=None, max_failures=None, max_rollbacks=None,
                 quarantine_path=None, logger=None):
        from .. import config as _config
        self.checkpoint_dir = checkpoint_dir
        self.interval = max(1, int(
            interval if interval is not None
            else _config.get("MXNET_GUARDIAN_INTERVAL")))
        self.window = max(2, int(
            window if window is not None
            else _config.get("MXNET_GUARDIAN_SPIKE_WINDOW")))
        self.spike_k = float(
            spike_k if spike_k is not None
            else _config.get("MXNET_GUARDIAN_SPIKE_K"))
        self.max_failures = int(
            max_failures if max_failures is not None
            else _config.get("MXNET_GUARDIAN_MAX_FAILURES"))
        self.max_rollbacks = int(
            max_rollbacks if max_rollbacks is not None
            else _config.get("MXNET_GUARDIAN_MAX_ROLLBACKS"))
        if quarantine_path is None:
            quarantine_path = str(
                _config.get("MXNET_GUARDIAN_QUARANTINE") or "")
            if not quarantine_path and checkpoint_dir is not None:
                os.makedirs(str(checkpoint_dir), exist_ok=True)
                quarantine_path = os.path.join(str(checkpoint_dir),
                                               "quarantine.jsonl")
        self.quarantine = QuarantineLog(quarantine_path) \
            if quarantine_path else None
        self._skip_positions = self.quarantine.batch_positions() \
            if self.quarantine is not None else set()
        self._logger = logger
        self.can_rollback = checkpoint_dir is not None
        self.in_graph = True     # fused step arms the health word on this
        # pending health tokens: [{'ok','sig','pos','k'}] — device arrays
        # until a poll materializes them (no per-step host sync)
        self._pending = []
        self._untagged = 0       # trailing pending entries without a pos
        self._steps_since_poll = 0
        self._gstep = 0          # trained-step counter (mirrors fit's)
        # spike detector state: EWMA + EW variance over LOG(signal) —
        # training signals decay exponentially, so a linear EWMA lags
        # orders of magnitude above the current level and hides real
        # spikes; in log space the decay is drift the variance absorbs
        self._ewma = None        # EWMA of log(signal)
        self._ewvar = 0.0        # EW variance of log(signal)
        self._history = 0        # finite signals folded in so far
        self._last_good_step = 0
        # policy state
        self._consecutive_failures = 0
        self._rollbacks = 0
        self.pending_rollback_step = None   # armed between request+restore
        # (lo, hi) gstep window the newest rollback disowned — consumed
        # by CheckpointPublisher to fence those versions out of the
        # model registry (loop/publisher.py)
        self.last_rollback_window = None
        self._shard_info = None  # last batch attribution (source, lo, hi)
        self._iterator = None
        self._allreduce = None   # kvstore reduction (multi-worker)
        self._kv_seen = _np.zeros(3, _np.float64)  # cumulative pulled
        self._sync_errors = 0
        self._stats = {"steps_observed": 0, "polls": 0, "skips": 0,
                       "spikes": 0, "rollbacks": 0, "quarantined": 0,
                       "sync_degraded": 0, "injected_nonfinite": 0,
                       "injected_spike": 0}
        # telemetry plane: skip/rollback/quarantine counters under the
        # stable 'guardian' namespace (weakly held — dies with the fit)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("guardian", self.stats)

    # -- wiring ---------------------------------------------------------------
    def attach(self, module):
        """Wire this guardian into a bound+optimized Module: the fused
        step computes the in-graph health word and conditional update;
        a multi-worker kvstore becomes the decision all-reduce.  Safe to
        call again after a restart rebuilds either."""
        fs = getattr(module, "_fused_step", None)
        if fs is not None and hasattr(fs, "attach_guardian"):
            fs.attach_guardian(self)
        kv = getattr(module, "_kvstore", None)
        if kv is not None and getattr(kv, "num_workers", 1) > 1:
            self._wire_kvstore(kv)

    def attach_iterator(self, data_iter):
        """Give the iterator the quarantine log (it appends corrupt
        records it detects) and apply already-quarantined records so a
        resumed run never re-reads a poisoned record."""
        self._iterator = data_iter
        if self.quarantine is None:
            return
        if hasattr(data_iter, "set_quarantine"):
            data_iter.set_quarantine(self.quarantine)
        if hasattr(data_iter, "apply_quarantine"):
            data_iter.apply_quarantine(self.quarantine.load())

    def _wire_kvstore(self, kv):
        """Health-bit all-reduce over the kvstore: every worker pushes
        its cumulative counters on a reserved key and pulls the sum, so
        one worker's local verdict (its shard fed it the bad batch)
        becomes everyone's decision.  Runs inside the supervisor's
        watchdog when one is active (`supervised`), so a dead worker
        surfaces as a CollectiveTimeoutError, not a hang."""
        from . import supervisor as _sup
        state = {"inited": False}
        key = "__guardian_health__"

        def allreduce(vec):
            from .. import nd

            def exchange():
                if not state["inited"]:
                    kv.init(key, nd.zeros(len(vec)))
                    state["inited"] = True
                kv.push(key, nd.array(_np.asarray(vec, _np.float32)))
                out = nd.zeros(len(vec))
                kv.pull(key, out)
                return out.asnumpy()

            return _sup.supervised("guardian.sync", exchange)

        self._allreduce = allreduce

    # -- fused-step side ------------------------------------------------------
    def step_multipliers(self, k):
        """One gradient multiplier per step of the upcoming block: 1.0
        normally; NaN when an injected ``grad.nonfinite`` clause fires
        for that step (the in-graph skip path's deterministic trigger);
        ``_SPIKE_SCALE`` when a ``loss.spike`` clause fires."""
        out = []
        for _ in range(k):
            self._gstep += 1
            gm = 1.0
            try:
                _faults.fire("grad.nonfinite", step=self._gstep)
            except Exception:
                gm = float("nan")
                self._stats["injected_nonfinite"] += 1
            try:
                _faults.fire("loss.spike", step=self._gstep)
            except Exception:
                gm = _SPIKE_SCALE
                self._stats["injected_spike"] += 1
            out.append(_np.float32(gm))
        return out

    def record_health(self, k, ok, sig):
        """Health word of the last dispatch: `ok`/`sig` are device
        scalars (k==1) or stacked device vectors (a K-step block).  No
        host sync here — `maybe_poll` materializes them in one gather."""
        self._pending.append({"ok": ok, "sig": sig, "k": int(k),
                              "pos": None})
        self._untagged += 1
        self._stats["steps_observed"] += int(k)
        if len(self._pending) > 1024:
            # a fused step driven outside the fit loop (no polls): cap
            # the token backlog instead of pinning device buffers forever
            drop = len(self._pending) - 1024
            self._pending = self._pending[drop:]
            self._untagged = min(self._untagged, len(self._pending))

    # -- fit-loop side --------------------------------------------------------
    def tag(self, epoch, nbatch0, data_iter=None):
        """Attach stream positions (epoch, first nbatch) to the health
        tokens the fused step recorded since the last tag — the fit loop
        calls this right after each processed block, so a later poll can
        quarantine a bad step by position."""
        first_nbatch = int(nbatch0)
        if self._untagged:
            for entry in self._pending[-self._untagged:]:
                entry["pos"] = (int(epoch), int(nbatch0))
                nbatch0 += entry["k"]
            self._untagged = 0
        it = data_iter if data_iter is not None else self._iterator
        if it is not None and hasattr(it, "record_range"):
            try:
                self._shard_info = it.record_range(first_nbatch)
            except Exception:
                pass

    def should_skip(self, epoch, nbatch):
        """Whether this stream position is quarantined (skip without
        training; positions still advance so resume bookkeeping stays
        aligned with the run that wrote the quarantine)."""
        return (int(epoch), int(nbatch)) in self._skip_positions

    def note_skipped(self, epoch, nbatch):
        _record_event("quarantine-skip", epoch=int(epoch),
                      nbatch=int(nbatch))

    def maybe_poll(self, gstep, force=False):
        """Materialize pending health tokens and run the policy ladder —
        every ``interval`` trained steps (or on `force`: checkpoint
        boundaries, epoch ends).  Raises `RollbackRequested` on a
        diagnosed spike, `TrainingDivergedError` past the budget."""
        if not self._pending:
            return
        pending_steps = sum(e["k"] for e in self._pending)
        if not force and pending_steps < self.interval:
            return
        self._stats["polls"] += 1
        tokens = self._classify(self._materialize())
        local = self._ladder_inputs(tokens)
        agreed = self._agree(local)
        self._apply_ladder(agreed, tokens, gstep)

    def _materialize(self):
        """One blocking gather of every pending device token ->
        [(pos, step_offset, ok, sig)] flattened per step."""
        import jax
        pending, self._pending = self._pending, []
        self._untagged = 0
        leaves = []
        for e in pending:
            leaves.append(e["ok"])
            leaves.append(e["sig"])
        host = jax.device_get(leaves)
        out = []
        # pending tokens are exactly the last sum(k) dispatched steps,
        # ending at the fused step's counter (_gstep) — rollback-safe
        base_step = self._gstep - sum(e["k"] for e in pending)
        consumed = 0
        for i, e in enumerate(pending):
            ok = _np.atleast_1d(_np.asarray(host[2 * i]))
            sig = _np.atleast_1d(_np.asarray(host[2 * i + 1]))
            for j in range(e["k"]):
                pos = None
                if e["pos"] is not None:
                    pos = (e["pos"][0], e["pos"][1] + j)
                out.append((pos, base_step + consumed + 1,
                            float(ok[j]), float(sig[j])))
                consumed += 1
        return out

    def _classify(self, raw):
        """Classify each materialized token ONCE against the detector
        state as it stood when the token's step ran (folding in-bounds
        signals as it walks) -> [(pos, step, ok, sig, is_spike)]."""
        out = []
        contaminated = False
        for pos, step, ok, sig in raw:
            spike = False
            if ok >= 0.5 and not contaminated:
                spike = self._is_spike(sig)
                if not spike:
                    self._fold(sig)
                    self._last_good_step = max(self._last_good_step, step)
            # once a spike appears, the later steps of this window
            # trained on contaminated parameters: they must neither
            # advance last_good nor feed the EWMA.  A non-finite step
            # does NOT contaminate — its update was refused in-graph.
            if spike:
                contaminated = True
            out.append((pos, step, ok, sig, spike))
        return out

    def _ladder_inputs(self, tokens):
        """Local health bits: [n_bad, n_spike, first_spike_step]."""
        n_bad = sum(1 for _, _, ok, _, _ in tokens if ok < 0.5)
        n_spike = sum(1 for *_, spike in tokens if spike)
        spike_step = next((step for _, step, _, _, spike in tokens
                           if spike), 0)
        return _np.asarray([n_bad, n_spike, spike_step], _np.float64)

    def _is_spike(self, sig):
        """Spike test: a k-sigma relative jump of log(signal) over its
        EWMA AND an absolute displacement past
        ``_SPIKE_MIN_DISPLACEMENT`` — the signal is the per-step
        parameter-displacement ratio, so the absolute gate means the
        step genuinely moved the parameters by a damaging fraction."""
        if self._history < self.window or self._ewma is None:
            return False
        if sig <= _SPIKE_MIN_DISPLACEMENT:
            return False
        logsig = math.log(max(sig, _LOG_FLOOR))
        sigma = min(max(math.sqrt(max(self._ewvar, 0.0)), _SIGMA_LO),
                    _SIGMA_HI)
        return logsig - self._ewma > self.spike_k * sigma

    def _fold(self, sig):
        """Fold one in-bounds signal into the log-space EWMA/variance."""
        logsig = math.log(max(sig, _LOG_FLOOR))
        if self._ewma is None:
            self._ewma = logsig
            self._ewvar = 0.0
        else:
            alpha = 2.0 / (self.window + 1.0)
            delta = logsig - self._ewma
            self._ewma += alpha * delta
            self._ewvar = (1.0 - alpha) * (self._ewvar
                                           + alpha * delta * delta)
        self._history += 1

    def _ewma_linear(self):
        """The EWMA back in signal units (for stamps/messages/stats)."""
        return None if self._ewma is None else math.exp(self._ewma)

    def _agree(self, local):
        """All-reduce the local health bits so every worker takes the
        same decision.  In synchronous data-parallel training every
        worker observes the identical health word, so the sum is n x the
        local value; the reduction matters for the asymmetric case — one
        worker's shard fed it the bad batch — where the OR of the flags
        (sum > 0) propagates the verdict.  Degrades to the local bits
        (counted) when the store cannot reduce."""
        if self._allreduce is None:
            return local
        try:
            pulled = _np.asarray(self._allreduce(list(local)), _np.float64)
            # the store SUMS every worker's pushes across polls: this
            # poll's verdict is the delta against what was already seen
            total = pulled - self._kv_seen
            self._kv_seen = pulled
            if total[1] > 0 and local[1] == 0:
                # a peer diagnosed the spike: adopt its step (mean of the
                # diagnosing workers — identical when symmetric)
                total[2] = total[2] / max(round(total[1]), 1)
            elif local[1] > 0:
                total[2] = local[2]
            return total
        except Exception as e:
            self._sync_errors += 1
            self._stats["sync_degraded"] += 1
            if self._logger is not None:
                self._logger.warning(
                    "guardian: health-bit reduction unavailable (%s); "
                    "falling back to local decisions", str(e)[:200])
            return local

    def _apply_ladder(self, agreed, tokens, gstep):
        n_bad, n_spike = int(round(agreed[0])), int(round(agreed[1]))
        spike_step = int(round(agreed[2]))
        # the failure BUDGET counts steps, not worker-copies of a step:
        # in synchronous data-parallel training every worker reports the
        # same bad step, so the agreed sum is world_size x the step
        # count — budget on the LOCAL count (floored at 1 when only a
        # peer saw the bad step, so the verdict still registers)
        local_bad = sum(1 for _, _, ok, _, _ in tokens if ok < 0.5)
        budget_bad = max(local_bad, 1 if n_bad else 0)
        # rung 1: skip-batch — the in-graph select already refused the
        # update; here the skipped positions are quarantined and counted
        if n_bad:
            for pos, step, ok, sig, _ in tokens:
                if ok >= 0.5:
                    continue
                self._quarantine(pos, step, "nonfinite", sig)
                self._stats["skips"] += 1
                _record_event("skip-batch", step=step,
                              epoch=pos[0] if pos else -1,
                              nbatch=pos[1] if pos else -1)
                _add_finding(
                    "skip-batch",
                    f"non-finite gradients at step {step} — the update "
                    "was not applied (in-graph skip); the batch position "
                    "is quarantined", f"step{step}")
            self._consecutive_failures += budget_bad
        # rung 2: rollback — a spiking update was already applied
        if n_spike:
            self._stats["spikes"] += 1
            self._consecutive_failures += 1
            sig = next((s for *_, s, spike in tokens if spike),
                       float("nan"))
            self._check_budget(spike_step or gstep, sig)
            quarantined = []
            for pos, step, ok, s, spike in tokens:
                # the spike window: the diagnosed step and everything
                # after it in this poll (updates already contaminated)
                if ok >= 0.5 and (spike or (spike_step and
                                            step >= spike_step)):
                    self._quarantine(pos, step, "loss-spike", s)
                    if pos is not None:
                        quarantined.append(pos)
            if self.can_rollback:
                self._rollbacks += 1
                self._stats["rollbacks"] += 1
                if self._rollbacks > self.max_rollbacks:
                    raise TrainingDivergedError(
                        spike_step or gstep, signal=sig,
                        shard=self._shard_desc(),
                        reason=f"{self._rollbacks - 1} rollback(s) already "
                               "spent (MXNET_GUARDIAN_MAX_ROLLBACKS)")
                self.pending_rollback_step = self._last_good_step
                self.last_rollback_window = (
                    self._last_good_step + 1, int(spike_step or gstep))
                _record_event("rollback", step=spike_step or gstep,
                              last_good_step=self._last_good_step)
                # the EWMA may be unset when a PEER diagnosed the spike
                # (fresh detector after rollback_committed, late joiner)
                ew = self._ewma_linear()
                _add_finding(
                    "rollback",
                    f"loss spike at step {spike_step or gstep} (signal "
                    f"{sig:.6g} vs EWMA "
                    f"{'?' if ew is None else format(ew, '.6g')}) — "
                    "rolling back to the newest healthy checkpoint at "
                    f"step <= {self._last_good_step}", f"step{spike_step}")
                raise RollbackRequested(spike_step or gstep,
                                        self._last_good_step, sig,
                                        quarantined)
            _add_finding(
                "spike-unrecoverable",
                f"loss spike at step {spike_step or gstep} (signal "
                f"{sig:.6g}) but no checkpoint_dir to roll back to — "
                "training continues on the spiked parameters; pass "
                "checkpoint_dir= to Module.fit to arm rollback",
                f"step{spike_step}")
        if not n_bad and not n_spike:
            self._consecutive_failures = 0
        else:
            bad_step = next((st for _, st, ok, _, _ in tokens
                             if ok < 0.5), gstep)
            self._check_budget(bad_step, float("nan") if n_bad else None)

    def _check_budget(self, step, signal):
        if self._consecutive_failures > self.max_failures:
            _record_event("diverged", step=int(step))
            raise TrainingDivergedError(
                step, signal=signal, shard=self._shard_desc(),
                reason=f"{self._consecutive_failures} consecutive "
                       "unhealthy step(s) (MXNET_GUARDIAN_MAX_FAILURES="
                       f"{self.max_failures})")

    def _quarantine(self, pos, step, reason, signal):
        if pos is not None:
            self._skip_positions.add(pos)
        self._stats["quarantined"] += 1
        _record_event("quarantine", step=int(step), reason=reason)
        if self.quarantine is None:
            return
        entry = {"reason": reason, "step": int(step),
                 "signal": None if signal is None or
                 not math.isfinite(signal) else float(signal)}
        if pos is not None:
            entry["epoch"], entry["nbatch"] = int(pos[0]), int(pos[1])
        shard = self._shard_desc()
        if shard:
            entry["shard"] = shard
        self.quarantine.append(**entry)

    def _shard_desc(self):
        info = self._shard_info
        if not info:
            return None
        try:
            source, lo, hi = info
            return f"{source}[{lo}:{hi}]"
        except Exception:
            return str(info)

    # -- checkpoint side ------------------------------------------------------
    def health_stamp(self):
        """The ``health`` block a checkpoint manifest carries: rollback
        selects only checkpoints stamped healthy (an unstamped manifest
        — pre-guardian — counts as healthy for compatibility)."""
        status = "healthy" if self._consecutive_failures == 0 and \
            self.pending_rollback_step is None else "suspect"
        stamp = {"status": status,
                 "signal_ewma": self._ewma_linear(),
                 "skips": self._stats["skips"],
                 "rollbacks": self._rollbacks}
        return stamp

    def rollback_committed(self, step):
        """A rollback restore landed: clear the pending request and the
        spike detector's history (the replayed window re-folds fresh) —
        the failure counter survives, so thrashing rollbacks still
        escalate to TrainingDivergedError."""
        self.pending_rollback_step = None
        self._ewma = None
        self._ewvar = 0.0
        self._history = 0
        self._pending = []
        self._untagged = 0
        self._last_good_step = int(step)
        self._gstep = int(step)
        _record_event("rollback-committed", step=int(step))

    def stats(self):
        out = dict(self._stats)
        out.update(consecutive_failures=self._consecutive_failures,
                   signal_ewma=self._ewma_linear(),
                   quarantine_path=self.quarantine.path
                   if self.quarantine is not None else None,
                   pending_rollback_step=self.pending_rollback_step)
        return out

    def close(self):
        if self.quarantine is not None:
            self.quarantine.close()
