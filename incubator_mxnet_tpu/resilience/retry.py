"""Retry policy: exponential backoff with jitter, deadlines, and a budget.

`RetryPolicy` is the single retry currency for the dist transport and the
serving batcher: per-attempt delay grows geometrically from ``base_delay``
to ``max_delay`` with multiplicative jitter, bounded by ``max_attempts``
and/or an overall ``deadline`` (seconds from the first attempt), and
optionally charged against a shared `RetryBudget` so a cluster-wide
brownout cannot turn every caller into a retry storm (the classic retry
amplification failure).

Jitter is drawn from a policy-local seeded stream: under a seeded fault
schedule the whole retry timeline is reproducible bit for bit.
"""
from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "RetryBudget"]


class RetryBudget:
    """Token bucket shared across callers: each retry spends one token,
    tokens refill at ``refill_per_s``.  When the bucket is dry, callers
    stop retrying and surface the error — retries are a scarce resource
    during a real outage, not a right."""

    def __init__(self, capacity=16, refill_per_s=1.0,
                 clock=time.monotonic):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._tokens = self.capacity
        self._last = clock()

    def acquire(self):
        now = self._clock()
        self._tokens = min(self.capacity,
                           self._tokens + (now - self._last) *
                           self.refill_per_s)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class RetryPolicy:
    """Backoff schedule: attempt k (0-based retry index) sleeps
    ``min(base_delay * multiplier**k, max_delay) * (1 + U[0,jitter))``."""

    def __init__(self, max_attempts=4, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, deadline=None, budget=None,
                 seed=None, sleep=time.sleep, clock=time.monotonic):
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline if deadline is None else float(deadline)
        self.budget = budget
        self.seed = seed
        self._sleep = sleep
        self._clock = clock

    def delay(self, retry_index, rng=None):
        """The backoff delay before retry `retry_index` (0-based)."""
        d = min(self.base_delay * self.multiplier ** retry_index,
                self.max_delay)
        if self.jitter and rng is not None:
            d *= 1.0 + rng.random() * self.jitter
        return d

    def delays(self):
        """Generator of sleep durations — one per permitted retry.
        Exhausts after ``max_attempts - 1`` retries, when the overall
        deadline would be passed, or when the shared budget runs dry."""
        rng = random.Random(self.seed) if self.jitter else None
        t_end = None if self.deadline is None \
            else self._clock() + self.deadline
        for k in range(max(self.max_attempts - 1, 0)):
            if t_end is not None and self._clock() >= t_end:
                return
            if self.budget is not None and not self.budget.acquire():
                return
            yield self.delay(k, rng)

    def call(self, fn, retry_on=(ConnectionError, EOFError, OSError),
             on_retry=None):
        """Run ``fn()`` under this policy.  ``on_retry(attempt, exc)``
        observes each failure that will be retried; the final failure
        (attempts/deadline/budget exhausted) propagates."""
        delays = self.delays()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as exc:
                delay = next(delays, None)
                if delay is None:
                    raise
                attempt += 1
                if on_retry is not None:
                    on_retry(attempt, exc)
                self._sleep(delay)
