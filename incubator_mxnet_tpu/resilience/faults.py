"""Deterministic fault injection.

Production failure modes — refused connections, mid-message socket drops,
slow peers, servers that die after N requests, torn checkpoint writes —
are injected at NAMED SITES compiled into the dist/serving/checkpoint
layers.  A site is one `fire(site, **ctx)` call on the failure-prone
path; with no faults configured the call is a function call plus one
global read (no locks, no syscalls, no allocation), so production code
pays nothing for being testable.

Faults come from the ``MXNET_FAULTS`` environment spec or the
programmatic `inject()` API.  Spec grammar (clauses joined with ``;``)::

    MXNET_FAULTS = clause (';' clause)*
    clause       = 'seed=' INT
                 | site ':' kind [ '(' key '=' value (',' key '=' value)* ')' ]
    site         = transport.connect | transport.send | transport.recv
                 | server.dispatch | serving.execute | checkpoint.commit
                 | heartbeat.send | collective.dispatch | host.step
                 | router.dispatch | replica.health | replica.swap
                 | grad.nonfinite | loss.spike | io.corrupt_record
                 | publish.commit | canary.eval
    kind         = refuse | drop | slow | crash | torn | error | hang | kill
                 | corrupt

Firing controls (any clause):

* ``at=N`` / ``at=N-M``  — fire on the Nth (or Nth..Mth) matching hit only
* ``n=N``                — fire on the first N matching hits
* ``p=F``                — fire with probability F from the SEEDED stream
* ``cmd=NAME``           — only hits whose context carries ``cmd=NAME``
* ``record=N``           — only hits whose context carries ``record=N``
  (exact record targeting at payload sites: hit-count controls are
  schedule-order dependent when a multi-threaded reader drives the
  site, ``record=`` is deterministic regardless of thread interleaving)

The supervisor sites model pod-scale failures: ``heartbeat.send`` with a
``drop`` skips one heartbeat (lossy control network), ``collective.
dispatch`` with a ``hang`` sleeps inside the dispatched collective (the
lost-host stall the watchdog must convert into an error), and
``host.step`` with a ``kill`` hard-exits the whole process (SIGKILL-grade
host loss, exit code 137) — the three ingredients of a deterministic
in-process pod chaos schedule.

The serving-router sites model replica-fleet failures (serving/router.py):
``router.dispatch`` fires per dispatch attempt (an ``error`` there is a
failed hand-off), ``replica.health`` fires per health probe (a ``drop``
burst is a lossy probe network — it must cause suspicion, not
eviction), and ``replica.swap`` fires before each replica's weight swap
(a ``torn`` there is a swap that dies mid-roll — the fleet must keep
serving and the roll must abort cleanly).

The training-guardian sites model SILENT training failures
(resilience/guardian.py): ``grad.nonfinite`` fires once per fused train
step — an ``error`` there is converted by the guardian into an in-graph
non-finite gradient for exactly that step (the skip-batch path's
deterministic trigger); ``loss.spike`` fires the same way but scales the
step's gradients by a large factor instead (the rollback path's
trigger); and ``io.corrupt_record`` fires per record read through the
`mutate()` payload hook — a ``corrupt`` clause there bit-flips the
record's bytes deterministically, so record-level corruption is
injectable without hand-built fixture files.

The train-to-serve loop sites (loop/): ``publish.commit`` fires once
per registry publish — a ``torn`` clause there leaves a TRUNCATED
version manifest under the final name (the publisher "died" mid-
rename), which every registry reader must treat as invisible, and a
``slow`` clause delays the publish (freshness-lag pressure);
``canary.eval`` fires before each canary holdout evaluation — an
``error`` there is a broken scoring path the controller must fail
CLOSED (an unscorable candidate is a rejected one, never a promoted
one), and ``slow`` models a canary that eats into the freshness SLO.

The ``corrupt`` kind only fires through `mutate(site, payload)` (it
needs bytes to damage); `fire()` ignores corrupt clauses entirely, so a
site instrumented with both hooks keeps deterministic hit counting.
Clause args: ``bytes=N`` bytes flipped (default 16), ``offset=K`` pins
the first flipped byte.

Every fired fault appends an event to an in-process trace
(`resilience.trace()`), and — when ``MXNET_FAULTS_LOG`` names a file —
one JSON line per event.  Every event carries this process's pid and
DMLC rank, and each line is written with a single ``O_APPEND`` write, so
the processes of a multi-host chaos run can share ONE log file without
interleaving or clobbering each other's events.  The same seed always
produces the same schedule: hit counters and the Bernoulli stream are
both deterministic.
"""
from __future__ import annotations

import os
import random
import re
import time

from ..base import MXNetError
from ..analysis import locks as _alocks

__all__ = ["FaultInjected", "TornWrite", "configure", "inject", "clear",
           "reset", "trace", "fire", "mutate", "note", "active",
           "parse_spec"]


class FaultInjected(Exception):
    """Base of every injected failure that surfaces as an exception."""

    def __init__(self, site, kind, message=""):
        self.site = site
        self.kind = kind
        super().__init__(message or f"fault-injected {kind} at {site}")


class TornWrite(FaultInjected):
    """Checkpoint writer 'died' mid-commit (see checkpoint/snapshot.py)."""


_KINDS = ("refuse", "drop", "slow", "crash", "torn", "error", "hang",
          "kill", "corrupt")
_CLAUSE_RE = re.compile(
    r"^(?P<site>[\w.]+):(?P<kind>\w+)(?:\((?P<args>[^)]*)\))?$")


class _Clause:
    """One parsed fault clause with its own deterministic hit counter."""

    def __init__(self, site, kind, args, seed):
        if kind not in _KINDS:
            raise MXNetError(f"MXNET_FAULTS: unknown fault kind {kind!r} "
                             f"(one of {', '.join(_KINDS)})")
        self.site = site
        self.kind = kind
        self.args = args
        self.hits = 0          # matching-site hits observed
        self.fired = 0         # faults actually fired
        at = args.get("at")
        if at is not None and "-" in str(at):
            lo, hi = str(at).split("-", 1)
            self.at = (int(lo), int(hi))
        elif at is not None:
            self.at = (int(at), int(at))
        else:
            self.at = None
        self.limit = int(args["n"]) if "n" in args else None
        self.prob = float(args["p"]) if "p" in args else None
        self.cmd = args.get("cmd")
        self.record = int(args["record"]) if "record" in args else None
        # each probabilistic clause draws from its OWN seeded stream so
        # adding a clause never perturbs another clause's schedule
        self._rng = random.Random((seed, site, kind, repr(sorted(
            args.items()))).__repr__()) if self.prob is not None else None

    def matches(self, site, ctx):
        if site != self.site:
            return False
        if self.cmd is not None and ctx.get("cmd") != self.cmd:
            return False
        if self.record is not None and ctx.get("record") != self.record:
            return False
        return True

    def evaluate(self):
        """Advance this clause's hit counter (and Bernoulli stream) and
        report whether it WOULD fire.  The caller increments `fired` only
        for the clause actually executed, so a clause shadowed by an
        earlier one on the same hit does not silently burn its n= budget."""
        self.hits += 1
        draw = self._rng.random() if self._rng is not None else None
        if self.at is not None and not (self.at[0] <= self.hits <= self.at[1]):
            return False
        if self.limit is not None and self.fired >= self.limit:
            return False
        if draw is not None and draw >= self.prob:
            return False
        return True


def _parse_args(text):
    args = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        if not sep:
            raise MXNetError(f"MXNET_FAULTS: bad clause arg {part!r} "
                             "(want key=value)")
        args[key.strip()] = value.strip()
    return args


def parse_spec(spec, seed=0):
    """Parse an ``MXNET_FAULTS`` spec string -> (clauses, seed)."""
    clauses = []
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if raw.startswith("seed="):
            seed = int(raw[5:])
            continue
        m = _CLAUSE_RE.match(raw)
        if m is None:
            raise MXNetError(f"MXNET_FAULTS: cannot parse clause {raw!r} "
                             "(want site:kind(key=val,...))")
        clauses.append((m.group("site"), m.group("kind"),
                        _parse_args(m.group("args"))))
    return clauses, seed


# -- global state -------------------------------------------------------------
# ACTIVE is the hot-path gate: False means fire() returns after ONE global
# read.  None means "MXNET_FAULTS not parsed yet" (first fire parses it).
ACTIVE = None
_lock = _alocks.make_lock("resilience.faults")   # taken only while faults are configured
_clauses = []
_trace = []
_seed = 0
_log_path = None


def _load_env():
    global ACTIVE, _seed, _log_path
    spec = os.environ.get("MXNET_FAULTS", "")
    _log_path = os.environ.get("MXNET_FAULTS_LOG") or None
    clauses, _seed = parse_spec(spec, 0)
    for site, kind, args in clauses:
        _clauses.append(_Clause(site, kind, args, _seed))
    ACTIVE = bool(_clauses)


def active():
    """Whether any fault clause is configured."""
    if ACTIVE is None:
        with _lock:
            if ACTIVE is None:
                _load_env()
    return bool(ACTIVE)


def configure(spec, seed=None):
    """Install a full fault schedule from a spec string (replaces any
    previous schedule; counters and trace reset)."""
    global ACTIVE, _seed
    clauses, parsed_seed = parse_spec(spec, seed if seed is not None else 0)
    with _lock:
        _clauses.clear()
        _trace.clear()
        _seed = parsed_seed if seed is None else seed
        for site, kind, args in clauses:
            _clauses.append(_Clause(site, kind, args, _seed))
        ACTIVE = bool(_clauses)


def inject(site, kind, **args):
    """Add one fault clause programmatically, e.g.
    ``inject('transport.send', 'drop', at=2, cmd='push')``."""
    global ACTIVE
    active()   # fold in any env-configured clauses first
    with _lock:
        _clauses.append(_Clause(site, kind,
                                {k: str(v) for k, v in args.items()}, _seed))
        ACTIVE = True


def clear():
    """Remove every fault clause and the trace (ACTIVE goes False —
    the hot path returns to its one-global-read cost)."""
    global ACTIVE
    with _lock:
        _clauses.clear()
        _trace.clear()
        ACTIVE = False


def reset():
    """Reset hit counters and the trace, keeping the configured clauses
    (reruns of a schedule start from hit 1 again)."""
    with _lock:
        _trace.clear()
        for c in _clauses:
            c.hits = 0
            c.fired = 0
            if c._rng is not None:
                c._rng = random.Random((_seed, c.site, c.kind, repr(sorted(
                    c.args.items()))).__repr__())


def trace():
    """Every fired fault so far: [{site, kind, hit, seq, ctx}]."""
    with _lock:
        return [dict(e) for e in _trace]


def _record(event):
    # every event names its emitting process AND thread (the shared
    # sink's pid/rank/thread stamping — obs.jsonl_sink — so chaos and
    # sanitizer artifacts attribute a fired fault to the router health
    # loop vs a dispatch thread vs a supervisor heartbeat, not just to
    # "the process"; the rank is read per event because the
    # shrink-and-resume path re-ranks a live process mid-run)
    from ..obs import jsonl_sink as _jsonl
    _jsonl.stamp(event)
    _trace.append(event)
    if _log_path is not None:
        # O_APPEND + one write() per line (the sink's contract): every
        # process of a chaos run appends to the SAME file without
        # interleaving mid-line
        _jsonl.sink(_log_path).write(event)
    try:
        from .. import profiler as _profiler
        _profiler.record_fault(event.get("site"), event.get("kind"),
                               **event.get("ctx", {}))
    except Exception:
        pass   # a fault event must never take the injected code path down


def note(event, **ctx):
    """Log a non-fault event (retry, reconnect, recovery) into the same
    trace/log stream so chaos artifacts can count them next to the
    faults that caused them.  No-op when no schedule is configured."""
    if not active():
        return
    with _lock:
        _record({"event": event, "site": ctx.pop("site", None), "kind": None,
                 "ctx": {k: v for k, v in ctx.items()
                         if isinstance(v, (str, int, float, bool))}})


def fire(site, **ctx):
    """The site hook.  Returns instantly when no faults are configured;
    otherwise evaluates each matching clause's deterministic schedule and
    executes the first fault that fires (raise / sleep / socket close).
    ``corrupt`` clauses never fire here — they need bytes to damage and
    only fire through `mutate()` (payload sites call that hook instead),
    so they neither advance nor consume hits on a `fire()`-only site."""
    if not ACTIVE:
        if ACTIVE is None:
            active()
            if not ACTIVE:
                return
        else:
            return
    clause = None
    with _lock:
        # every matching clause's hit counter and Bernoulli stream
        # advance on every hit — whether another clause fired first or
        # not — so one clause's schedule never perturbs another's; only
        # the clause actually executed consumes its n= budget
        for c in _clauses:
            if c.kind == "corrupt":
                continue
            if c.matches(site, ctx) and c.evaluate() and clause is None:
                clause = c
        if clause is None:
            return
        clause.fired += 1
        event = {"event": "fault", "site": site, "kind": clause.kind,
                 "hit": clause.hits, "seq": len(_trace) + 1,
                 "ctx": {k: v for k, v in ctx.items()
                         if isinstance(v, (str, int, float, bool))}}
        _record(event)
    _execute(clause, site, ctx)


def mutate(site, payload, **ctx):
    """The payload-site hook: `fire()` plus the ``corrupt`` kind.

    Called on paths that hold the bytes a fault could damage (record
    reads at ``io.corrupt_record``).  Returns `payload` untouched when
    nothing fires; a firing ``corrupt`` clause returns a deterministic
    bit-flipped copy (seeded by the schedule seed x site x hit, so the
    same spec always damages the same bytes of the same record); any
    other firing kind executes exactly as `fire()` would (raise/sleep).
    """
    if not ACTIVE:
        if ACTIVE is None:
            active()
            if not ACTIVE:
                return payload
        else:
            return payload
    clause = None
    with _lock:
        for c in _clauses:
            if c.matches(site, ctx) and c.evaluate() and clause is None:
                clause = c
        if clause is None:
            return payload
        clause.fired += 1
        event = {"event": "fault", "site": site, "kind": clause.kind,
                 "hit": clause.hits, "seq": len(_trace) + 1,
                 "ctx": {k: v for k, v in ctx.items()
                         if isinstance(v, (str, int, float, bool))}}
        _record(event)
        hit = clause.hits
    if clause.kind != "corrupt":
        _execute(clause, site, ctx)
        return payload
    data = bytearray(payload)
    if not data:
        return payload
    n = min(int(clause.args.get("bytes", 16)), len(data))
    rng = random.Random((_seed, site, hit).__repr__())
    if "offset" in clause.args:
        start = int(clause.args["offset"]) % len(data)
        positions = [(start + i) % len(data) for i in range(n)]
    else:
        positions = rng.sample(range(len(data)), n)
    for pos in positions:
        # XOR with a non-zero seeded byte: every chosen position is
        # guaranteed to actually change
        data[pos] ^= rng.randint(1, 255)
    return bytes(data)


def _execute(clause, site, ctx):
    kind = clause.kind
    if kind == "slow":
        time.sleep(float(clause.args.get("ms", 100)) / 1e3)
        return
    if kind == "refuse":
        raise ConnectionRefusedError(
            f"fault-injected connection refused at {site}")
    if kind == "drop":
        # mid-message drop: tear the socket down under the caller so the
        # peer sees a half-frame + EOF, then surface the reset locally
        sock = ctx.get("sock")
        if sock is not None:
            try:
                sock.sendall(b"\x00\x00\x00")   # torn length prefix
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        raise ConnectionResetError(
            f"fault-injected mid-message connection drop at {site}")
    if kind == "crash":
        raise FaultInjected(site, "crash",
                            f"fault-injected server crash at {site}")
    if kind == "torn":
        raise TornWrite(site, "torn",
                        f"fault-injected torn write at {site}")
    if kind == "error":
        raise MXNetError(f"fault-injected error at {site}")
    if kind == "hang":
        # the lost-host stall: the call never returns on its own (default
        # 1h — far past any watchdog deadline); ms= bounds it for tests
        # that want the hang to eventually clear
        time.sleep(float(clause.args.get("ms", 3_600_000)) / 1e3)
        return
    if kind == "kill":
        # whole-host death: no atexit, no flush, no unwinding — the
        # SIGKILL-grade loss the membership deadline must detect (the
        # default code is the conventional 128+SIGKILL)
        os._exit(int(clause.args.get("code", 137)))
