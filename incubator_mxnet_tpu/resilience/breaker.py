"""Circuit breaker: fail fast on a peer that keeps failing.

Classic three-state machine (closed -> open -> half-open -> closed):

* **closed** — requests flow; ``failure_threshold`` CONSECUTIVE failures
  trip the breaker (one success resets the count);
* **open** — `allow()` returns False (callers fail fast, no wire time
  wasted on a dead peer) until ``reset_timeout`` elapses;
* **half-open** — exactly one probe request is admitted; its success
  closes the breaker, its failure re-opens it for another full
  ``reset_timeout``.

Used per parameter server by `dist.kvstore_dist` (a tripped breaker
becomes a structured `ServerLostError`) and per served model by
`serving.batcher` (a tripped breaker sheds requests while half-open
probes test recovery).  The clock is injectable so scripted open/
half-open/close sequences are testable without sleeping.
"""
from __future__ import annotations

import time

from ..analysis import locks as _alocks

__all__ = ["CircuitBreaker"]

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    def __init__(self, failure_threshold=3, reset_timeout=5.0,
                 clock=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = _alocks.make_lock("resilience.breaker")
        self._state = CLOSED
        self._failures = 0          # consecutive failures while closed
        self._opened_at = None
        self._probe_out = False     # the half-open probe is in flight

    @property
    def state(self):
        with self._lock:
            return self._observe()

    @property
    def consecutive_failures(self):
        with self._lock:
            return self._failures

    def _observe(self):
        """State with the open -> half-open timer applied (lock held)."""
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout:
            self._state = HALF_OPEN
            self._probe_out = False
        return self._state

    def allow(self):
        """Whether a request may proceed now.  In half-open exactly one
        caller gets True (the probe); everyone else fails fast until the
        probe reports back."""
        with self._lock:
            state = self._observe()
            if state == CLOSED:
                return True
            if state == HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def release_probe(self):
        """Return an admitted half-open probe WITHOUT recording an
        outcome — for callers that admitted a request via `allow()` but
        then rejected it before it ever executed (shed, oversized,
        queue-full).  Without this the probe token leaks and the breaker
        wedges in half_open forever."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_out = False

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._probe_out = False
            self._state = CLOSED

    def record_failure(self):
        """One failure.  Returns True when this failure tripped (or
        re-tripped) the breaker open."""
        with self._lock:
            state = self._observe()
            if state == HALF_OPEN:
                # the probe failed: back to a full open window
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_out = False
                return True
            self._failures += 1
            if state == CLOSED and self._failures >= self.failure_threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                return True
            return False
