"""Versioned symbol-JSON upgrade (reference `src/nnvm/legacy_json_util.cc`).

Old MXNet releases serialized graphs the loader of a newer release must
still accept.  The reference runs an ordered upgrader list over the parsed
graph (`legacy_json_util.cc:187-219`); the same passes are re-expressed
here as dict-level rewrites applied before `symbol.load_json` builds nodes:

* < 0.9.0   — aux-state variables (BatchNorm moving mean/var, ...) were not
  serialized: append `{node}_{arg}` variable nodes for the missing trailing
  inputs (`UpgradeJSON_000800_000900`, legacy_json_util.cc:135).
* < 0.9.4   — optimizer hints (lr_mult/wd_mult/...) were stored as plain
  attrs, possibly `arg_mult`-suffixed onto the op node: move them to
  `__key__` form, suffixed ones onto the referenced input variable
  (`UpgradeJSON_FixParsing`, :49, kHiddenKeys from c_api_symbolic.cc:40).
* < 0.9.5   — argmin/argmax serialized `axis="-1"` to mean "flatten all":
  drop the attr so the modern default applies (`UpgradeJSON_000904_000905`,
  :175).

Unknown attrs that newer parsers reject are otherwise preserved verbatim —
`Symbol.load_json` decides what to do with them.
"""
from __future__ import annotations

import json

CURRENT_VERSION = 10200

# c_api_symbolic.cc:40 kHiddenKeys
HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
               "mirror_stage")


def _node_attrs(jn):
    # the attr dict itself moved names over time: param -> attr -> attrs
    for key in ("attrs", "attr", "param"):
        if key in jn:
            return jn[key], key
    jn["attrs"] = {}
    return jn["attrs"], "attrs"


def _expected_inputs(op_name, attrs):
    from ..ops import registry as _reg
    op = _reg.maybe_get(op_name)
    if op is None:
        return None
    try:
        params = op.canonicalize_params(dict(attrs))
    except Exception:
        params = {k: v for k, v in op.params.items()
                  if v is not _reg.REQUIRED}
    names = op.list_input_names(params)
    if names is not None:
        return names
    n = op.num_inputs(params)
    return [f"arg{i}" for i in range(n)] if n >= 0 else None


def _upgrade_add_aux_vars(g):
    """< 0.9.0: re-create unserialized trailing variable inputs.

    New variables are inserted immediately before their consuming op so the
    node list stays topologically ordered (loaders build sequentially);
    every index in inputs/arg_nodes/heads is remapped.
    """
    old_nodes = g["nodes"]
    new_nodes = []
    remap = {}
    new_args = []
    for idx, jn in enumerate(old_nodes):
        jn = dict(jn)
        jn["inputs"] = [[remap[e[0]], *e[1:]] for e in jn["inputs"]]
        if jn["op"] != "null":
            attrs, _ = _node_attrs(jn)
            names = _expected_inputs(jn["op"], attrs)
            if names is not None:
                for i in range(len(jn["inputs"]), len(names)):
                    var_name = (f"{jn['name']}_{names[i]}" if jn["name"]
                                else names[i])
                    new_nodes.append({"op": "null", "name": var_name,
                                      "attrs": {}, "inputs": []})
                    new_args.append(len(new_nodes) - 1)
                    jn["inputs"].append([len(new_nodes) - 1, 0, 0])
        remap[idx] = len(new_nodes)
        new_nodes.append(jn)
    g["nodes"] = new_nodes
    g["arg_nodes"] = sorted([remap[i] for i in g.get("arg_nodes", [])]
                            + new_args)
    if "heads" in g:
        g["heads"] = [[remap[e[0]], *e[1:]] for e in g["heads"]]
    g.pop("node_row_ptr", None)
    return g


def _upgrade_hidden_keys(g):
    """< 0.9.4: plain lr_mult/wd_mult/... attrs -> __key__ form."""
    nodes = g["nodes"]
    for jn in nodes:
        attrs, akey = _node_attrs(jn)
        moved = {}
        for k in list(attrs):
            for hk in HIDDEN_KEYS:
                if k == hk:
                    moved[f"__{hk}__"] = attrs.pop(k)
                    break
                if k.endswith("_" + hk):
                    # `{arg}_lr_mult` on the op node belongs on the {arg}
                    # input variable
                    arg = k[: -len(hk) - 1]
                    names = _expected_inputs(jn["op"], attrs) or []
                    if arg in names:
                        i = names.index(arg)
                        if i < len(jn["inputs"]):
                            tgt = nodes[jn["inputs"][i][0]]
                            if tgt["op"] == "null":
                                tattrs, _ = _node_attrs(tgt)
                                tattrs[f"__{hk}__"] = attrs.pop(k)
                                break
                    moved[f"__{hk}__"] = attrs.pop(k)
                    break
        attrs.update(moved)
        if akey != "attrs":
            jn["attrs"] = jn.pop(akey)
    return g


def _upgrade_argmax_axis(g):
    """< 0.9.5: argmin/argmax axis="-1" meant the modern default."""
    for jn in g["nodes"]:
        if jn["op"] in ("argmin", "argmax"):
            attrs, _ = _node_attrs(jn)
            if attrs.get("axis") == "-1":
                del attrs["axis"]
    return g


_UPGRADERS = [
    (10000, _upgrade_hidden_keys),
    (900, _upgrade_add_aux_vars),
    (905, _upgrade_argmax_axis),
]


def upgrade_json(json_str_or_dict):
    """Apply every upgrade pass newer than the graph's recorded version.

    Mirrors `LoadLegacyJSONPass` (`legacy_json_util.cc:195-219`): missing
    version metadata means 0.8.0 (800).
    """
    g = (json.loads(json_str_or_dict) if isinstance(json_str_or_dict, str)
         else json_str_or_dict)
    version = 800
    attrs = g.get("attrs", {})
    if isinstance(attrs, dict) and "mxnet_version" in attrs:
        v = attrs["mxnet_version"]
        version = int(v[1] if isinstance(v, (list, tuple)) else v)
    for threshold, fn in sorted(_UPGRADERS):
        if threshold > version:
            g = fn(g)
    g.setdefault("attrs", {})["mxnet_version"] = ["int", CURRENT_VERSION]
    return g
