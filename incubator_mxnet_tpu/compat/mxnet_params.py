"""Reference `.params` binary container — reader and writer.

Byte layout (from `src/ndarray/ndarray.cc`):

file      := u64 0x112 (kMXAPINDArrayListMagic) . u64 reserved=0
           . u64 n . ndarray*n                  (dmlc vector<NDArray>)
           . u64 m . (u64 len . bytes)*m        (dmlc vector<string> names)
ndarray   := u32 0xF993fac9 (NDARRAY_V2_MAGIC, `ndarray.cc:1535`)
           . i32 stype                          (0 dense, 1 row_sparse, 2 csr)
           . [shape storage_shape]              (iff stype sparse)
           . shape                              (logical shape)
           . i32 dev_type . i32 dev_id          (Context::Save, base.h:188)
           . i32 type_flag                      (mshadow TypeFlag)
           . (i32 aux_type . shape aux_shape)*nad
           . raw data bytes                     (storage_shape for sparse)
           . raw aux bytes * nad
shape     := u32 ndim . i64*ndim                (nnvm::Tuple::Save, int64
                                                 since NDARRAY_V1_MAGIC)

Legacy pre-V2 arrays (`ndarray.cc:1603-1648`): the leading u32 is either
NDARRAY_V1_MAGIC (0xF993fac8, shape as above) or the raw ndim itself with
u32 dims (pre-V1); no stype/aux sections.  All little-endian.
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError

LIST_MAGIC = 0x112
V1_MAGIC = 0xF993FAC8
V2_MAGIC = 0xF993FAC9

# mshadow::TypeFlag (mshadow/base.h)
_TYPE_TO_NP = {0: "<f4", 1: "<f8", 2: "<f2", 3: "|u1", 4: "<i4", 5: "|i1",
               6: "<i8"}
_NP_TO_TYPE = {np.dtype(v): k for k, v in _TYPE_TO_NP.items()}

_STYPE_DENSE, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2
_NUM_AUX = {_STYPE_DENSE: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, n):
        if self.pos + n > len(self.buf):
            raise MXNetError("Invalid NDArray file format (truncated)")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]

    def shape(self):
        ndim = self.u32()
        return tuple(struct.unpack(f"<{ndim}q", self.read(8 * ndim)))


def _read_ndarray(r: _Reader):
    magic = r.u32()
    if magic != V2_MAGIC:
        return _read_legacy(r, magic)
    stype = r.i32()
    nad = _NUM_AUX.get(stype)
    if nad is None:
        raise MXNetError(f"Unknown storage type {stype} in .params file")
    sshape = r.shape() if nad > 0 else None
    shape = r.shape()
    if len(shape) == 0:
        return None
    r.i32(); r.i32()                      # context (dev_type, dev_id): unused
    type_flag = r.i32()
    dt = _TYPE_TO_NP.get(type_flag)
    if dt is None:
        raise MXNetError(f"Unsupported dtype flag {type_flag}")
    aux = []
    for _ in range(nad):
        at = r.i32()
        ashape = r.shape()
        aux.append((_TYPE_TO_NP[at], ashape))
    data_shape = sshape if nad else shape
    n = int(np.prod(data_shape)) if data_shape else 1
    data = np.frombuffer(r.read(n * np.dtype(dt).itemsize),
                         dtype=dt).reshape(data_shape)
    aux_arrays = []
    for at, ashape in aux:
        an = int(np.prod(ashape)) if ashape else 1
        aux_arrays.append(np.frombuffer(
            r.read(an * np.dtype(at).itemsize), dtype=at).reshape(ashape))
    if stype == _STYPE_DENSE:
        return data
    return _to_sparse(stype, shape, data, aux_arrays)


def _read_legacy(r: _Reader, magic):
    if magic == V1_MAGIC:
        shape = r.shape()
    else:
        ndim = magic                      # pre-V1: the word IS the ndim
        shape = tuple(struct.unpack(f"<{ndim}I", r.read(4 * ndim)))
    if len(shape) == 0:
        return None
    r.i32(); r.i32()                      # context
    type_flag = r.i32()
    dt = _TYPE_TO_NP.get(type_flag)
    if dt is None:
        raise MXNetError(f"Unsupported dtype flag {type_flag}")
    n = int(np.prod(shape))
    return np.frombuffer(r.read(n * np.dtype(dt).itemsize),
                         dtype=dt).reshape(shape)


def _to_sparse(stype, shape, data, aux_arrays):
    from ..ndarray import sparse as sp
    if stype == _STYPE_ROW_SPARSE:
        return sp.RowSparseNDArray(
            data=data, indices=aux_arrays[0].astype("int64"), shape=shape)
    # csr aux order in the container: indptr then indices (`ndarray.cc`
    # kIndPtr=0, kIdx=1 for CSR)
    return sp.CSRNDArray(
        data=data, indices=aux_arrays[1].astype("int64"),
        indptr=aux_arrays[0].astype("int64"), shape=shape)


def load_params(fname_or_bytes):
    """Read a reference `.params`/`.nd` container -> dict name->NDArray
    (or list when the file carries no names, as `mx.nd.load` does)."""
    if isinstance(fname_or_bytes, (bytes, bytearray, memoryview)):
        buf = bytes(fname_or_bytes)
    else:
        with open(fname_or_bytes, "rb") as f:
            buf = f.read()
    r = _Reader(buf)
    if r.u64() != LIST_MAGIC:
        raise MXNetError("Invalid NDArray file format (bad list magic)")
    r.u64()                               # reserved
    n = r.u64()
    arrays = [_read_ndarray(r) for _ in range(n)]
    m = r.u64()
    names = [r.read(r.u64()).decode() for _ in range(m)]
    if names and len(names) != len(arrays):
        raise MXNetError("Invalid NDArray file format (name/array mismatch)")

    from ..ndarray.ndarray import NDArray, array
    def wrap(a):
        if a is None or isinstance(a, NDArray):
            return a
        a = np.ascontiguousarray(a)
        return array(a, dtype=a.dtype)
    wrapped = [wrap(a) for a in arrays]
    if not names:
        return wrapped
    return dict(zip(names, wrapped))


def _shape_bytes(shape):
    return struct.pack("<I", len(shape)) + struct.pack(
        f"<{len(shape)}q", *shape)


def _write_ndarray(out, arr):
    from ..ndarray import sparse as sp
    from ..ndarray.ndarray import NDArray
    if isinstance(arr, sp.RowSparseNDArray):
        data, aux = arr._np_data, [arr._np_indices.astype("<i8")]
        stype, shape = _STYPE_ROW_SPARSE, arr.shape
    elif isinstance(arr, sp.CSRNDArray):
        data = arr._np_data
        aux = [arr._np_indptr.astype("<i8"), arr._np_indices.astype("<i8")]
        stype, shape = _STYPE_CSR, arr.shape
    else:
        data = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
        aux, stype, shape = [], _STYPE_DENSE, data.shape
    dt = np.dtype(data.dtype)
    if dt not in _NP_TO_TYPE:
        # bf16 & friends have no reference type flag: save as f4
        data = data.astype("<f4")
        dt = np.dtype("<f4")
    out.append(struct.pack("<I", V2_MAGIC))
    out.append(struct.pack("<i", stype))
    if stype != _STYPE_DENSE:
        out.append(_shape_bytes(data.shape))
    out.append(_shape_bytes(shape))
    out.append(struct.pack("<ii", 1, 0))  # Context: cpu(0)
    out.append(struct.pack("<i", _NP_TO_TYPE[dt]))
    for a in aux:
        out.append(struct.pack("<i", _NP_TO_TYPE[np.dtype(a.dtype)]))
        out.append(_shape_bytes(a.shape))
    out.append(np.ascontiguousarray(data).tobytes())
    for a in aux:
        out.append(np.ascontiguousarray(a).tobytes())


def save_params(fname, data, names=None):
    """Write the reference container.  data: dict name->array or list."""
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        arrays = list(data)
        names = list(names) if names is not None else []
    out = [struct.pack("<QQ", LIST_MAGIC, 0), struct.pack("<Q", len(arrays))]
    for a in arrays:
        _write_ndarray(out, a)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        out.append(struct.pack("<Q", len(b)) + b)
    blob = b"".join(out)
    if fname is None:
        return blob
    with open(fname, "wb") as f:
        f.write(blob)
    return None
