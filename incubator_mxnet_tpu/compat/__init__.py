"""Byte-level compatibility with reference MXNet artifacts.

* `mxnet_params` — the dmlc binary NDArray container (`.params` files,
  `src/ndarray/ndarray.cc:1531-1761`): read AND write, dense + row_sparse
  + csr, including the pre-0.8 legacy per-array headers.
* `legacy_json` — the versioned symbol-JSON upgrade passes
  (`src/nnvm/legacy_json_util.cc:49-219`) re-expressed over the JSON dict.
"""
from . import legacy_json, mxnet_params
from .mxnet_params import load_params, save_params

__all__ = ["mxnet_params", "legacy_json", "load_params", "save_params"]
