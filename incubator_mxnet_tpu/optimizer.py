"""Optimizers (reference `python/mxnet/optimizer.py`).

Each optimizer's `update` dispatches to the fused update ops
(`ops/optimizer_ops.py` — the reference's `src/operator/optimizer_op.cc`
kernels, here XLA-compiled with dynamic lr/wd scalars), or composes nd ops
for the long-tail optimizers.  `create_state_multi_precision` keeps fp32
master weights for low-precision params (reference `optimizer.py:201`) — the
TPU-relevant case is bf16 weights.
"""
from __future__ import annotations

import math
import pickle

import numpy

from .base import MXNetError
from .ndarray import ndarray as _nd
from .ndarray.ndarray import NDArray
from . import ndarray as nd

__all__ = ["Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD",
           "Adam", "AdaGrad", "AdaDelta", "RMSProp", "Ftrl", "Adamax",
           "Nadam", "LBSGD", "Test", "Updater", "get_updater", "create",
           "register"]


class Optimizer:
    """Base optimizer (reference `optimizer.py:Optimizer`)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        if sym is not None:
            self.sym_info = (sym.attr_dict(), sym.list_arguments())
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for low-precision weights (reference :201)."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype in (numpy.float16,):
            weight_master_copy = weight.astype("float32")
            return (weight_master_copy,) + (self.create_state(index,
                                                              weight_master_copy),)
        if weight.dtype.name == "bfloat16" and self.multi_precision:
            weight_master_copy = weight.astype("float32")
            return (weight_master_copy,) + (self.create_state(index,
                                                              weight_master_copy),)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) \
                and isinstance(state[0], NDArray) \
                and state[0].dtype == numpy.float32 \
                and weight.dtype != numpy.float32:
            w32, base_state = state[0], state[1]
            g32 = grad.astype("float32")
            self.update(index, w32, g32, base_state)
            w32.copyto(weight)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def state_dict(self):
        """Host-serializable snapshot of the optimizer's SCALAR state —
        update counters and the LR-scheduler position (the tensors live
        in `Updater.states` and travel as checkpoint shards).  What the
        elastic checkpoint manifest records so a resumed run schedules
        learning rates exactly where the interrupted one stopped."""
        d = {"num_update": int(self.num_update),
             "begin_num_update": int(self.begin_num_update),
             "index_update_count": {str(k): int(v) for k, v in
                                    self._index_update_count.items()}}
        if self.lr_scheduler is not None:
            d["lr_scheduler"] = self.lr_scheduler.state_dict()
        return d

    def load_state_dict(self, d):
        self.num_update = int(d.get("num_update", self.num_update))
        self.begin_num_update = int(d.get("begin_num_update",
                                          self.begin_num_update))
        counts = d.get("index_update_count")
        if counts is not None:
            self._index_update_count = {
                (int(k) if str(k).lstrip("-").isdigit() else k): int(v)
                for k, v in counts.items()}
        if self.lr_scheduler is not None and d.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(d["lr_scheduler"])

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("param_dict", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.param_dict = {}


register = Optimizer.register


def _clip(og):
    return og if og is not None and og > 0 else -1.0


# ---------------------------------------------------------------------------
# Lazy row-sparse updates (reference `src/operator/optimizer_op.cc`
# sgd/adam `lazy_update` kernels): when the gradient is a RowSparseNDArray
# (embedding-style workloads), only the TOUCHED rows of the weight and the
# optimizer state are read, updated, and scattered back — one jitted
# gather→update→scatter program per signature instead of densifying the
# gradient over the full table.  Untouched rows keep weight AND state
# unchanged (the reference's documented lazy semantics).
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=None)
def _lazy_sgd_jit(momentum):
    import jax

    def run(w, m, idx, g, lr, wd, rescale, clip):
        rows_w = w[idx]
        g = g * rescale
        g = jax.numpy.where(clip > 0, jax.numpy.clip(g, -clip, clip), g)
        g = (g + wd * rows_w).astype(w.dtype)
        if momentum:
            new_m = momentum * m[idx] - lr.astype(w.dtype) * g
            return w.at[idx].add(new_m), m.at[idx].set(new_m)
        return w.at[idx].add(-lr.astype(w.dtype) * g), m

    # no donation: callers may hold aliases (detach() shares the buffer)
    return jax.jit(run)


@_functools.lru_cache(maxsize=None)
def _lazy_adam_jit(beta1, beta2, eps):
    import jax
    jnp = jax.numpy

    def run(w, mean, var, idx, g, lr, wd, rescale, clip):
        rows_w = w[idx]
        g = g * rescale
        g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
        g = (g + wd * rows_w).astype(w.dtype)
        new_mean = beta1 * mean[idx] + (1 - beta1) * g
        new_var = beta2 * var[idx] + (1 - beta2) * jnp.square(g)
        upd = lr.astype(w.dtype) * new_mean / (jnp.sqrt(new_var) + eps)
        return (w.at[idx].add(-upd), mean.at[idx].set(new_mean),
                var.at[idx].set(new_var))

    # no donation: callers may hold aliases (detach() shares the buffer)
    return jax.jit(run)


_EMPTY_ROWS = object()


def _row_sparse_grad(grad):
    """(indices, values) of a row-sparse grad, `_EMPTY_ROWS` when it has no
    touched rows (the lazy contract: a no-op step, NOT a dense decay), or
    None for dense grads."""
    from .ndarray.sparse import RowSparseNDArray, aggregate_row_sparse
    if isinstance(grad, RowSparseNDArray):
        if len(grad._np_indices) == 0:
            return _EMPTY_ROWS
        # duplicate ids (one batch touching a row twice) must pre-sum:
        # the lazy kernels scatter state rows with .at[idx].set, which is
        # last-write-wins under duplicates
        return aggregate_row_sparse(grad._np_indices, grad._np_data)
    return None


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (reference `optimizer.py:445`)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype.name in ("float16", "bfloat16"):
            w32 = weight.astype("float32")
            mom = nd.zeros(weight.shape, ctx=weight.context, dtype="float32") \
                if self.momentum != 0.0 else None
            return (mom, w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        rs = _row_sparse_grad(grad) if self.lazy_update else None
        if rs is _EMPTY_ROWS:
            return  # no touched rows: lazy step is a no-op
        if rs is not None:
            import numpy as _onp
            idx, vals = rs
            run = _lazy_sgd_jit(float(self.momentum))
            mom = state._data if state is not None else \
                _onp.zeros((1,), weight.dtype)
            new_w, new_m = run(weight._data, mom, idx,
                               vals.astype(weight.dtype),
                               _onp.float32(lr), _onp.float32(wd),
                               _onp.float32(self.rescale_grad),
                               _onp.float32(_clip(self.clip_gradient)))
            weight._set_data(new_w)
            if state is not None:
                state._set_data(new_m)
            return
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, momentum=self.momentum,
                              out=weight, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if isinstance(state, tuple) and len(state) == 2 and \
                isinstance(state[1], NDArray) and state[1].dtype == numpy.float32 \
                and weight.dtype != numpy.float32:
            self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            mom, w32 = state
            kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient))
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32,
                                     momentum=self.momentum, out=weight, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, out=weight, **kw)
        else:
            self.update(index, weight, grad, state)


@register
class Signum(Optimizer):
    """Reference `optimizer.py:550 Signum` (signSGD + momentum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            nd.signum_update(weight, grad, state, momentum=self.momentum,
                             wd_lh=self.wd_lh, out=weight, **kw)
        else:
            nd.signsgd_update(weight, grad, out=weight, **kw)


@register
class FTML(Optimizer):
    """Reference `optimizer.py:616 FTML`."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        v_new = self.beta2 * v + (1 - self.beta2) * g * g
        d_new = (1 - pow(self.beta1, t)) / lr * (
            (v_new / (1 - pow(self.beta2, t))).sqrt() + self.epsilon)
        sigma = d_new - self.beta1 * d
        z_new = self.beta1 * z + (1 - self.beta1) * g - sigma * weight
        new_w = -z_new / d_new
        d._set_data(d_new._data)
        v._set_data(v_new._data)
        z._set_data(z_new._data)
        weight._set_data(new_w._data.astype(weight.dtype))


@register
class DCASGD(Optimizer):
    """Reference `optimizer.py DCASGD` (delay-compensated async SGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mom, previous_weight = state
        d = g + wd * weight + self.lamda * g * g * (weight - previous_weight)
        if mom is not None:
            new_mom = self.momentum * mom - lr * d
            mom._set_data(new_mom._data)
            delta = new_mom
        else:
            delta = -lr * d
        weight._set_data((previous_weight * 0 + weight + delta)._data)
        previous_weight._set_data(weight._data)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference `optimizer.py NAG`)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        if state is not None:
            mom = state
            new_mom = self.momentum * mom + g + wd * weight
            new_w = weight - lr * (g + self.momentum * new_mom + wd * weight)
            mom._set_data(new_mom._data)
            weight._set_data(new_w._data.astype(weight.dtype))
        else:
            weight._set_data((weight - lr * (g + wd * weight))._data)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference `optimizer.py SGLD`)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype="float32", ctx=weight.context)
        weight._set_data(
            (weight - lr / 2 * (g + wd * weight) + noise)._data.astype(weight.dtype))


@register
class Adam(Optimizer):
    """Reference `optimizer.py Adam` — fused `adam_update` with bias-corrected lr."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        # ** 0.5, not math.sqrt: works for host floats AND the traced step
        # counts the fused train path injects (fused.py _apply_traced)
        lr = lr * coef2 ** 0.5 / coef1
        mean, var = state
        rs = _row_sparse_grad(grad) if self.lazy_update else None
        if rs is _EMPTY_ROWS:
            return  # no touched rows: lazy step is a no-op
        if rs is not None:
            import numpy as _onp
            idx, vals = rs
            run = _lazy_adam_jit(float(self.beta1), float(self.beta2),
                                 float(self.epsilon))
            new_w, new_mean, new_var = run(
                weight._data, mean._data, var._data, idx,
                vals.astype(weight.dtype), _onp.float32(lr),
                _onp.float32(wd), _onp.float32(self.rescale_grad),
                _onp.float32(_clip(self.clip_gradient)))
            weight._set_data(new_w)
            mean._set_data(new_mean)
            var._set_data(new_var)
            return
        nd.adam_update(weight, grad, mean, var, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=_clip(self.clip_gradient), out=weight)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        g = g + wd * weight
        hist = state
        new_hist = hist + g * g
        hist._set_data(new_hist._data)
        weight._set_data(
            (weight - lr * g / ((new_hist + self.float_stable_eps).sqrt()))._data
            .astype(weight.dtype))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        delta = ((acc_delta + self.epsilon).sqrt() /
                 (new_acc_g + self.epsilon).sqrt()) * g
        new_acc_delta = self.rho * acc_delta + (1 - self.rho) * delta * delta
        acc_g._set_data(new_acc_g._data)
        acc_delta._set_data(new_acc_delta._data)
        weight._set_data((weight - wd * weight - delta)._data.astype(weight.dtype))


@register
class RMSProp(Optimizer):
    """Reference `optimizer.py RMSProp` (centered=True uses rmspropalex)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, weight.context),
                    nd.zeros(weight.shape, weight.context),
                    nd.zeros(weight.shape, weight.context))
        return (nd.zeros(weight.shape, weight.context),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient),
                  gamma1=self.gamma1, epsilon=self.epsilon)
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, **kw)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  gamma2=self.gamma2, out=weight, **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, lr=lr, wd=wd, lamda1=self.lamda1,
                       beta=self.beta, rescale_grad=self.rescale_grad,
                       clip_gradient=_clip(self.clip_gradient), out=weight)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        m_t, u_t = state
        new_m = self.beta1 * m_t + (1.0 - self.beta1) * g
        new_u = nd.maximum(self.beta2 * u_t, nd.abs(g))
        m_t._set_data(new_m._data)
        u_t._set_data(new_u._data)
        weight._set_data((weight - lr * new_m / new_u)._data.astype(weight.dtype))


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        new_m = self.beta1 * m_t + (1.0 - self.beta1) * g
        new_v = self.beta2 * v_t + (1.0 - self.beta2) * g * g
        grad_prime = g / (1.0 - self.m_schedule)
        m_t_prime = new_m / (1.0 - m_schedule_next)
        v_t_prime = new_v / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        m_t._set_data(new_m._data)
        v_t._set_data(new_v._data)
        weight._set_data(
            (weight - lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon))._data
            .astype(weight.dtype))


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style scaling (reference `optimizer.py LBSGD`);
    warmup handled by the lr scheduler here."""

    def __init__(self, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(**kwargs)


@register
class Test(Optimizer):
    """Reference test optimizer."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad)._data)
        state._set_data(weight._data)


create = Optimizer.create_optimizer


class Updater:
    """KVStore updater closure (reference `optimizer.py:Updater`)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def set_states(self, states):
        states = pickle.loads(states) if isinstance(states, bytes) else states
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer) if dump_optimizer
                            else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
