"""`mx.sym.random` (reference `python/mxnet/symbol/random.py`)."""
from .symbol import Symbol, _sym_apply


def uniform(low=0, high=1, shape=(), dtype="float32", **kwargs):
    if isinstance(low, Symbol):
        return _sym_apply("_sample_uniform", [low, high],
                          {"shape": shape, "dtype": dtype, **kwargs})
    return _sym_apply("_random_uniform", [],
                      {"low": low, "high": high, "shape": shape,
                       "dtype": dtype, **kwargs})


def normal(loc=0, scale=1, shape=(), dtype="float32", **kwargs):
    if isinstance(loc, Symbol):
        return _sym_apply("_sample_normal", [loc, scale],
                          {"shape": shape, "dtype": dtype, **kwargs})
    return _sym_apply("_random_normal", [],
                      {"loc": loc, "scale": scale, "shape": shape,
                       "dtype": dtype, **kwargs})


def gamma(alpha=1, beta=1, shape=(), dtype="float32", **kwargs):
    if isinstance(alpha, Symbol):
        return _sym_apply("_sample_gamma", [alpha, beta],
                          {"shape": shape, "dtype": dtype, **kwargs})
    return _sym_apply("_random_gamma", [],
                      {"alpha": alpha, "beta": beta, "shape": shape,
                       "dtype": dtype, **kwargs})


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kwargs):
    return _sym_apply("_sample_multinomial", [data],
                      {"shape": shape, "get_prob": get_prob, "dtype": dtype})


def shuffle(data, **kwargs):
    return _sym_apply("_shuffle", [data], kwargs)
