"""Symbol: the symbolic graph API.

Re-expression of the reference's `nnvm::Symbol`/`Graph` + python surface
(`python/mxnet/symbol/symbol.py`).  A Symbol is a DAG of op nodes over
variable leaves; composition is pure bookkeeping (no compute).  Binding a
Symbol produces an `Executor` (`executor.py`) that compiles the whole graph
into ONE XLA computation — the TPU-native generalization of the reference's
GraphExecutor + bulk-exec segments (`src/executor/graph_executor.cc:1194-1316`:
where the reference fuses consecutive engine ops into segments, XLA compiles
the entire forward/backward as a single fused program).

Graph JSON (`tojson`/`load`) keeps the reference's schema — nodes with
{op, name, attrs, inputs}, arg_nodes, heads — so saved model structure is
interchangeable (`symbol.py:1192 save`, `src/nnvm/legacy_json_util.cc`).
"""
from __future__ import annotations

import json
import re
import threading

import numpy as _np

from ..base import MXNetError
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "check_unique_names"]


def check_unique_names(symbol):
    """Reject graphs whose VARIABLE names shadow each other (bind-time
    gate, called by the Executor).

    Two distinct nodes sharing a name where at least one is a variable
    break `arg_dict`: the dict collapses the duplicates and binding
    silently trains/feeds the wrong arrays.  Same-name OP pairs are
    tolerated — gluon's hybridize traces name every layer's op ``fwd``
    by design, and op identity is positional — the `mxlint`
    duplicate-name warning covers them.  Empty names always raise."""
    seen = {}
    for node in symbol._topo():
        if not str(node.name).strip():
            kind = "variable" if node.is_variable else f"op {node.op.name}"
            raise MXNetError(f"invalid graph: {kind} node has an empty "
                             "name")
        first = seen.get(node.name)
        if first is None:
            seen[node.name] = node
        elif node.is_variable or first.is_variable:
            raise MXNetError(
                f"invalid graph: two distinct nodes share the name "
                f"'{node.name}' "
                f"({'variable' if first.is_variable else first.op.name} vs "
                f"{'variable' if node.is_variable else node.op.name}); "
                "duplicate names silently shadow each other in "
                "arg_dict/tojson — rename one (mxlint: duplicate-name)")


class _NameManager:
    _tls = threading.local()

    @classmethod
    def next_name(cls, hint):
        if not hasattr(cls._tls, "counts"):
            cls._tls.counts = {}
        c = cls._tls.counts.get(hint, 0)
        cls._tls.counts[hint] = c + 1
        return f"{hint}{c}"


class _Node:
    """One graph node: an op application or a variable leaf."""

    __slots__ = ("op", "name", "attrs", "inputs", "_extra_attrs")

    def __init__(self, op, name, attrs, inputs):
        self.op = op              # OpDef or None for variables
        self.name = name
        self.attrs = attrs        # canonicalized op params
        self.inputs = inputs      # list[(Node, int out_index)]
        self._extra_attrs = {}    # user attrs (__shape__, lr_mult, ctx_group...)

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        if self.op is None:
            return 1
        return self.op.num_outputs(self.attrs)


class Symbol:
    """An output list over a graph (reference `Symbol`)."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)  # list[(Node, out_index)]

    # -- basic info ----------------------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __repr__(self):
        names = [n.name for n, _ in self._entries]
        return f"<Symbol {' '.join(names)}>"

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        for i in range(len(self._entries)):
            yield self[i]

    def __getitem__(self, index):
        if isinstance(index, str):
            outs = self.list_outputs()
            if index in outs:
                return Symbol([self._entries[outs.index(index)]])
            raise MXNetError(f"Cannot find output {index}")
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    def __copy__(self):
        return Symbol(self._entries)

    def __deepcopy__(self, memo):
        # graph nodes are immutable-by-convention; sharing is safe
        return Symbol(self._entries)

    # -- graph walks ---------------------------------------------------------
    def _topo(self):
        """Post-order topological node list (deterministic, DFS input order —
        matches the reference's DFSVisit ordering used for argument lists)."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for src, _ in node.inputs:
                visit(src)
            order.append(node)

        for node, _ in self._entries:
            visit(node)
        return order

    def _aux_node_ids(self):
        """Variable nodes feeding aux-state slots (BatchNorm running stats...)."""
        aux = set()
        for node in self._topo():
            if node.is_variable or not node.op:
                continue
            naux = node.op.num_aux(node.attrs)
            if naux:
                for src, _ in node.inputs[-naux:]:
                    if src.is_variable:
                        aux.add(id(src))
        return aux

    def list_arguments(self):
        """Reference `symbol.py list_arguments` (excludes aux states)."""
        aux = self._aux_node_ids()
        return [n.name for n in self._topo()
                if n.is_variable and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_node_ids()
        return [n.name for n in self._topo()
                if n.is_variable and id(n) in aux]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable]

    def list_outputs(self):
        out = []
        for node, idx in self._entries:
            if node.num_outputs() > 1:
                out.append(f"{node.name}_output{idx}")
            else:
                out.append(f"{node.name}_output")
        return out

    def get_internals(self):
        """All intermediate outputs as a grouped Symbol (reference
        `symbol.py get_internals`)."""
        entries = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        kids = []
        for node, _ in self._entries:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    # -- attributes ----------------------------------------------------------
    def attr(self, key):
        if len(self._entries) == 1:
            return self._entries[0][0]._extra_attrs.get(key)
        return None

    def _set_attr(self, **kwargs):
        for node, _ in self._entries:
            node._extra_attrs.update(kwargs)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            d = {}
            d.update({k: str(v) for k, v in node._extra_attrs.items()})
            if node.op is not None:
                d.update({k: str(v) for k, v in node.attrs.items()})
            if d:
                out[node.name] = d
        return out

    # -- shape/type inference -------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        shapes = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    shapes[n] = s
        shapes.update({k: v for k, v in kwargs.items() if v is not None})
        avals, out_avals, aux_avals = _infer_graph(self, shapes, partial)
        if avals is None:
            return None, None, None
        arg_shapes = [avals.get(n) for n in arg_names]
        aux_shapes = [avals.get(n) for n in aux_names]
        return (arg_shapes, out_avals, aux_shapes)

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dtypes = {}
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    dtypes[n] = t
        dtypes.update(kwargs)
        # types ride the same aval inference as shapes
        shapes_known = {}
        try:
            inferred = _infer_graph_types(self, dtypes)
        except Exception:
            return None, None, None
        arg_types = [inferred.get(n, _np.float32) for n in arg_names]
        aux_types = [inferred.get(n, _np.float32)
                     for n in self.list_auxiliary_states()]
        out_types = [_np.float32] * len(self._entries)
        return arg_types, out_types, aux_types

    # -- binding / eval -------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate argument/grad/aux arrays from inferred shapes and return an
        Executor (reference `symbol.py:1290 simple_bind` →
        `graph_executor.cc:1575`)."""
        from ..executor import Executor
        from ..context import current_context
        import os
        ctx = ctx or current_context()
        sym = self
        backend = os.environ.get("MXNET_SUBGRAPH_BACKEND")
        if backend:
            # reference build_subgraph.cc: env-selected backend partitions
            # the graph at bind time
            from ..subgraph import partition_graph
            sym = partition_graph(self, backend)
        return Executor._simple_bind(sym, ctx, grad_req, type_dict, kwargs,
                                     group2ctx=group2ctx)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        """Bind with caller-provided buffers (reference `symbol.py:1554 bind`)."""
        from ..executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req, aux_states)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def __call__(self, *args, **kwargs):
        """Composition: replace variable leaves with other symbols
        (reference Symbol.__call__/_compose)."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        name = kwargs.pop("name", None)
        if args and kwargs:
            raise MXNetError("compose only accepts input Symbols "
                             "either as positional or keyword arguments, not both")
        mapping = {}
        if args:
            free_vars = [n for n in self._topo() if n.is_variable]
            if len(args) > len(free_vars):
                raise MXNetError("too many positional inputs to compose")
            for node, sym in zip(free_vars, args):
                mapping[id(node)] = sym._entries[0]
        for k, v in kwargs.items():
            for node in self._topo():
                if node.is_variable and node.name == k:
                    mapping[id(node)] = v._entries[0]
        if not mapping:
            return
        remap = {}

        def rebuild(node):
            if id(node) in remap:
                return remap[id(node)]
            if id(node) in mapping:
                src, idx = mapping[id(node)]
                remap[id(node)] = src
                return src
            if node.is_variable:
                remap[id(node)] = node
                return node
            new_inputs = []
            for src, idx in node.inputs:
                ns = rebuild(src)
                new_inputs.append((ns, idx))
            nn = _Node(node.op, node.name, node.attrs, new_inputs)
            nn._extra_attrs = dict(node._extra_attrs)
            remap[id(node)] = nn
            return nn

        self._entries = [(rebuild(n), i) for n, i in self._entries]

    # -- gradient ------------------------------------------------------------
    def grad(self, wrt):
        raise MXNetError("Symbol.grad: bind with grad_req and use "
                         "Executor.backward (as the reference recommends)")

    # -- serialization ---------------------------------------------------------
    def tojson(self):
        nodes = self._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: str(v) for k, v in
                          (n.attrs.items() if n.op else
                           n._extra_attrs.items())},
                "inputs": [[nid[id(src)], idx, 0] for src, idx in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        heads = [[nid[id(n)], i, 0] for n, i in self._entries]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10200],
                                     "framework": ["str", "incubator_mxnet_tpu"]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- operator overloads ----------------------------------------------------
    def __add__(self, other):
        return _sym_binary(self, other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _sym_binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _sym_binary(self, other, None, "_rminus_scalar")

    def __mul__(self, other):
        return _sym_binary(self, other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _sym_binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _sym_binary(self, other, None, "_rdiv_scalar")

    def __pow__(self, other):
        return _sym_binary(self, other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _sym_apply("negative", [self], {})

    def __eq__(self, other):
        return _sym_binary(self, other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        return _sym_binary(self, other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _sym_binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _sym_binary(self, other, "broadcast_greater_equal",
                           "_greater_equal_scalar")

    def __lt__(self, other):
        return _sym_binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _sym_binary(self, other, "broadcast_lesser_equal",
                           "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)


# ---------------------------------------------------------------------------

_WALK_CAP = 2000  # composition-time name-check budget (see below)


def _reject_name_collision(names, entries, op_name):
    """Composition-time duplicate rejection for EXPLICITLY named ops: the
    new node's name and its to-be-auto-created parameter variable names
    must not collide with a VARIABLE already in the input graphs —
    `arg_dict` would collapse the duplicates and bind would train/feed
    the wrong arrays.  Same-name OP pairs stay legal (gluon names every
    layer's traced op ``fwd``; op identity is positional) and are left
    to the mxlint duplicate-name warning.  Auto-generated names are
    collision-free per thread (_NameManager counters), so only explicit
    names pay this walk — and the walk is CAPPED: past _WALK_CAP visited
    nodes (big unrolled graphs, where per-op walks go quadratic) the
    early build-time error is ceded to the O(n) bind-time gate
    `check_unique_names`, which enforces the same invariant."""
    seen = set()
    stack = [n for n, _ in entries]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        if len(seen) >= _WALK_CAP:
            return
        seen.add(id(node))
        if node.is_variable and node.name in names:
            raise MXNetError(
                f"cannot create op ({op_name}) named "
                f"'{sorted(names, key=len)[0]}': it would carry the name "
                f"'{node.name}', which already names a variable in the "
                "input graph; duplicate node names silently shadow each "
                "other in arg_dict/tojson — pick a unique name")
        stack.extend(src for src, _ in node.inputs)


def _sym_apply(op_name, inputs, kwargs):
    op = _reg.get(op_name)
    name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)
    if name is not None and not str(name).strip():
        raise MXNetError(f"Operator {op_name}: node name must be a "
                         "non-empty string")
    explicit_name = name is not None
    if op.variadic_param and op.variadic_param not in kwargs:
        kwargs[op.variadic_param] = len(inputs)
    params = op.canonicalize_params(kwargs)
    params.pop("ctx", None)
    if name is None:
        hint = re.sub("^_", "", op.name.lower())
        name = _NameManager.next_name(hint + "_" if not hint.endswith("_") else hint)
    entries = []
    for s in inputs:
        if not isinstance(s, Symbol):
            raise TypeError(f"Operator {op_name}: inputs must be Symbol, got "
                            f"{type(s).__name__}")
        if len(s._entries) != 1:
            raise MXNetError("cannot use a multi-output Symbol as an op input; "
                             "select one output first")
        entries.append(s._entries[0])
    # auto-create variables for missing trailing inputs (weights, biases, aux
    # states) — the reference does this in Symbol composition, producing the
    # canonical `{name}_weight` / `{name}_moving_mean` argument names
    from ..attribute import current_attrs
    scope_attrs = current_attrs()
    slot_names = op.list_input_names(params)
    if explicit_name:
        missing = slot_names[len(entries):] if slot_names is not None else []
        _reject_name_collision(
            {name} | {f"{name}_{slot}" for slot in missing}, entries,
            op.name)
    if slot_names is not None and len(entries) < len(slot_names):
        for slot in slot_names[len(entries):]:
            vnode = _Node(None, f"{name}_{slot}", {}, [])
            # auto-created parameters inherit the scope (ctx_group,
            # lr_mult, ...) like explicitly declared Variables do
            vnode._extra_attrs.update(scope_attrs)
            entries.append((vnode, 0))
    node = _Node(op, name, params, entries)
    if scope_attrs:
        node._extra_attrs.update(scope_attrs)
    if attr:
        node._extra_attrs.update(attr)
    nout = node.num_outputs()
    return Symbol([(node, i) for i in range(nout)]) if nout > 1 \
        else Symbol([(node, 0)])


def _sym_binary(lhs, rhs, tensor_op, scalar_op):
    if isinstance(rhs, Symbol):
        if tensor_op is None:
            raise TypeError("unsupported operand")
        return _sym_apply(tensor_op, [lhs, rhs], {})
    if isinstance(rhs, (int, float, bool)):
        return _sym_apply(scalar_op, [lhs], {"scalar": float(rhs)})
    return NotImplemented


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference `symbol.py Variable`)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    if not name.strip():
        raise MXNetError("variable name must be a non-empty string "
                         "(empty names cannot be addressed in arg_dict "
                         "or saved JSON)")
    node = _Node(None, name, {}, [])
    from ..attribute import current_attrs
    node._extra_attrs.update(current_attrs())
    if shape is not None:
        node._extra_attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        node._extra_attrs["__dtype__"] = dtype
    if lr_mult is not None:
        node._extra_attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        node._extra_attrs["__wd_mult__"] = wd_mult
    if init is not None:
        node._extra_attrs["__init__"] = init
    if attr:
        node._extra_attrs.update(attr)
    node._extra_attrs.update(kwargs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output Symbol (reference `symbol.py Group`)."""
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Rebuild a Symbol from graph JSON (reference `symbol.py:2566 load`,
    versioned loader `src/nnvm/legacy_json_util.cc:197-222`)."""
    from ..compat.legacy_json import upgrade_json
    g = upgrade_json(json_str)
    nodes = []
    for jn in g["nodes"]:
        attrs = {k: v for k, v in jn.get("attrs", jn.get("param", {})).items()}
        if jn["op"] == "null":
            node = _Node(None, jn["name"], {}, [])
            node._extra_attrs.update(attrs)
        else:
            op = _reg.get(jn["op"])
            params = op.canonicalize_params(attrs)
            params.pop("ctx", None)
            node = _Node(op, jn["name"],
                         params,
                         [(nodes[i], oi) for i, oi, *_ in jn["inputs"]])
        nodes.append(node)
    heads = g.get("heads")
    if heads:
        entries = [(nodes[i], oi) for i, oi, *_ in heads]
    else:
        entries = [(nodes[-1], 0)]
    return Symbol(entries)


# ---------------------------------------------------------------------------
# Graph-level inference helpers shared with the executor
# ---------------------------------------------------------------------------

def graph_eval_fn(symbol, is_train, n_rng_hint=None, scan=None):
    """Build a pure function (args_dict_values, aux_values, key) -> (outputs,
    new_aux) executing the graph.  This function is what the executor jits:
    the entire Symbol becomes ONE XLA computation.

    `scan` is an optional scan-over-layers plan
    (`analysis.graph_passes.scan_plan(symbol)`): each planned run of
    structurally identical layer blocks is emitted as ONE `lax.scan`
    body over per-layer parameters stacked INSIDE the traced program, so
    XLA compiles the layer body once instead of N inlined copies while
    arguments, aux states and checkpoints keep their per-layer layout.
    A run whose per-layer shapes turn out unequal at trace time (or
    whose carry changes shape) silently falls back to the inlined path —
    the plan is structural, shapes are only known here."""
    import jax
    import jax.numpy as jnp

    from ..ops import layout as _layout

    topo = symbol._topo()
    aux_ids = symbol._aux_node_ids()
    arg_nodes = [n for n in topo if n.is_variable and id(n) not in aux_ids]
    aux_nodes = [n for n in topo if n.is_variable and id(n) in aux_ids]
    rng_nodes = [n for n in topo if (not n.is_variable) and n.op.needs_rng]
    rng_index = {id(n): i for i, n in enumerate(rng_nodes)}
    use_nhwc = _layout.enabled()
    scan_first = {}
    if scan:
        for run in scan.get("runs", ()):
            scan_first[id(run["segments"][0][0])] = run

    def fn(arg_values, aux_values, key):
        env = {}
        for node, v in zip(arg_nodes, arg_values):
            env[id(node)] = (v,)
        aux_env = {}
        for node, v in zip(aux_nodes, aux_values):
            env[id(node)] = (v,)
            aux_env[id(node)] = v
        keys = jax.random.split(key, max(len(rng_nodes), 1))
        new_aux = dict(aux_env)
        # internal execution-layout pass (ops/layout.py): spatial ops run
        # NHWC (MXU-friendly), elementwise ops flow the tag through, every
        # other consumer and the graph heads see the API's NCHW — the
        # reference's cuDNN/MKLDNN layout selection done at graph level
        tags = {}

        def eval_node(node, e_env, e_tags, e_aux, key_for):
            params = dict(node.attrs)
            if node.op.mode_dependent:
                params["_train"] = bool(is_train)
            ins = [e_env[id(src)][idx] for src, idx in node.inputs]
            op_fn = node.op.fn
            out_tag = None
            if use_nhwc:
                in_tags = [e_tags.get((id(src), idx))
                           for src, idx in node.inputs]
                nat = _layout.NATIVE.get(node.op.name)
                if nat is not None and nat[1](node.op.name, params, ins[0]):
                    if in_tags[0] != "NHWC":
                        ins[0] = _layout.to_nhwc(ins[0])
                    # non-spatial slots (weights, vectors) must arrive in
                    # their API layout — untag any computed NHWC feed
                    ins[1:] = [_layout.to_nchw(v) if t == "NHWC" else v
                               for v, t in zip(ins[1:], in_tags[1:])]
                    op_fn = nat[0]
                    out_tag = "native"   # spatial output 0 only
                elif node.op.name in _layout.AGNOSTIC and \
                        any(t == "NHWC" for t in in_tags) and \
                        all(_layout.layout_safe_input(v, t)
                            for v, t in zip(ins, in_tags)):
                    out_tag = "all"
                else:
                    ins = [_layout.to_nchw(v) if t == "NHWC" else v
                           for v, t in zip(ins, in_tags)]
            if node.op.dynamic_params:
                for pname in node.op.dynamic_params:
                    ins.append(jnp.asarray(params.pop(pname), dtype="float32"))
            if node.op.needs_rng:
                ins.append(key_for(node))
            out = op_fn(params, *ins)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            nout = node.op.num_outputs(params)
            naux = node.op.num_aux(params)
            if naux and len(out) > nout:
                # write back aux updates
                for (src, _), upd in zip(node.inputs[-naux:], out[nout:]):
                    if id(src) in e_aux:
                        e_aux[id(src)] = upd
            e_env[id(node)] = tuple(out[:nout])
            if out_tag == "native":
                e_tags[(id(node), 0)] = "NHWC"
            elif out_tag == "all":
                for oi in range(nout):
                    e_tags[(id(node), oi)] = "NHWC"

        def main_key(node):
            return keys[rng_index[id(node)]]

        def try_scan_run(run):
            """Emit one planned run as lax.scan; False -> inline it."""
            length = run["length"]
            carry_src, carry_idx = run["carry"]
            c0 = env[id(carry_src)][carry_idx]
            if tags.get((id(carry_src), carry_idx)) == "NHWC":
                # scan carries cross in API layout (a lossless transpose
                # pair against the inlined path's flowing tag)
                c0 = _layout.to_nchw(c0)
            stacks, aux_stacks, key_stacks = [], [], []
            for slot_nodes in run["params"]:
                vals = [env[id(v)][0] for v in slot_nodes]
                s0 = (vals[0].shape, vals[0].dtype)
                if any((v.shape, v.dtype) != s0 for v in vals[1:]):
                    return False
                stacks.append(jnp.stack(vals))
            for slot_nodes in run["aux"]:
                vals = [new_aux[id(v)] for v in slot_nodes]
                s0 = (vals[0].shape, vals[0].dtype)
                if any((v.shape, v.dtype) != s0 for v in vals[1:]):
                    return False
                aux_stacks.append(jnp.stack(vals))
            for slot_nodes in run["rng"]:
                key_stacks.append(jnp.stack(
                    [keys[rng_index[id(n)]] for n in slot_nodes]))
            template = run["segments"][0]
            t_param = {id(v): s
                       for s, slots in enumerate(run["params"])
                       for v in (slots[0],)}
            t_aux_vars = [slots[0] for slots in run["aux"]]
            t_rng = {id(n): s for s, slots in enumerate(run["rng"])
                     for n in (slots[0],)}
            boundary0 = template[-1]

            def body(c, xs):
                pvals, avals, kvals = xs
                benv = {id(carry_src):
                        tuple(c if i == carry_idx else None
                              for i in range(carry_idx + 1))}
                for v, s in t_param.items():
                    benv[v] = (pvals[s],)
                baux = {}
                for v, a in zip(t_aux_vars, avals):
                    benv[id(v)] = (a,)
                    baux[id(v)] = a
                btags = {}
                for n in template:
                    eval_node(n, benv, btags, baux,
                              lambda m: kvals[t_rng[id(m)]])
                c_out = benv[id(boundary0)][0]
                if btags.get((id(boundary0), 0)) == "NHWC":
                    c_out = _layout.to_nchw(c_out)
                return c_out, tuple(baux[id(v)] for v in t_aux_vars)

            xs = (tuple(stacks), tuple(aux_stacks), tuple(key_stacks))
            xs0 = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), xs)
            try:
                c_aval = jax.eval_shape(lambda c, x: body(c, x)[0], c0, xs0)
            except Exception:
                return False
            if tuple(c_aval.shape) != tuple(c0.shape) or \
                    c_aval.dtype != c0.dtype:
                return False   # shape-changing block: scan carry invalid
            carry_out, ys = jax.lax.scan(body, c0, xs)
            env[id(run["boundary"])] = (carry_out,)
            for slot, layer_nodes in enumerate(run["aux"]):
                for li, v in enumerate(layer_nodes):
                    if id(v) in new_aux:
                        new_aux[id(v)] = ys[slot][li]
            skip.update(run["covered"])
            return True

        skip = set()
        for node in topo:
            if node.is_variable or id(node) in skip:
                continue
            run = scan_first.get(id(node))
            if run is not None and try_scan_run(run):
                continue
            eval_node(node, env, tags, new_aux, main_key)
        outputs = tuple(
            _layout.to_nchw(env[id(node)][idx])
            if tags.get((id(node), idx)) == "NHWC" else env[id(node)][idx]
            for node, idx in symbol._entries)
        aux_out = tuple(new_aux[id(n)] for n in aux_nodes)
        return outputs, aux_out

    return fn, arg_nodes, aux_nodes, len(rng_nodes)


def _infer_graph(symbol, shapes, partial):
    """Shape inference by abstract evaluation (replaces the InferShape
    fixpoint, `src/executor/infer_graph_attr_pass.cc:73`).

    Layout-marked variables with a 0 batch dim (RNN begin states) need the
    data batch size.  When a *bound input variable* carries an explicit
    ``__layout__`` attr ('NT'/'TN'/'NTC'/'TNC'), its N position identifies
    the batch dim authoritatively — that hint is tried first.  Only
    layout-less graphs fall back to probing each leading dim of the first
    bound shape and keeping the first that infers cleanly (which can guess
    wrong when batch == time; hence the layout preference).
    """
    hints = []
    for n in symbol._topo():
        if n.is_variable and n.name in shapes:
            layout = n._extra_attrs.get("__layout__")
            bound = tuple(shapes[n.name] or ())
            if layout:
                bpos = str(layout).find("N")
                if 0 <= bpos < len(bound) and bound[bpos] > 0:
                    hints.append(bound[bpos])
    first = next((tuple(v) for v in shapes.values()
                  if v and tuple(v) and tuple(v)[0] > 0), None)
    if first:
        hints += [d for d in first[:2] if d > 0]
    hints = list(dict.fromkeys(hints)) or [None]
    last_err = None
    for hint in hints:
        try:
            return _infer_graph_with_hint(symbol, shapes, partial, hint)
        except MXNetError as e:
            last_err = e
    raise last_err


def _infer_graph_with_hint(symbol, shapes, partial, batch_hint):
    import jax

    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    topo = symbol._topo()

    # seed known shapes: explicit kwargs beat __shape__ attrs
    known = {}
    for n in topo:
        if n.is_variable:
            cand = None
            if n.name in shapes:
                cand = tuple(shapes[n.name])
            elif "__shape__" in n._extra_attrs:
                cand = tuple(n._extra_attrs["__shape__"])
                layout = n._extra_attrs.get("__layout__")
                if cand and batch_hint is not None and layout:
                    bpos = str(layout).find("N")
                    if 0 <= bpos < len(cand) and cand[bpos] == 0:
                        cand = tuple(batch_hint if i == bpos else d
                                     for i, d in enumerate(cand))
            # shapes containing 0 are "unknown dims" (deferred init) — solve
            if cand is not None and all(d > 0 for d in cand):
                known[n.name] = cand

    # forward abstract interpretation with on-demand variable shape solving:
    # variables without shapes get inferred where unambiguous (weight shapes
    # from FullyConnected/Convolution attrs, like the reference's backward
    # shape inference); otherwise inference fails unless partial.
    env = {}
    missing = []

    def aval(shape, dtype=_np.float32):
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    for node in topo:
        if node.is_variable:
            if node.name in known:
                env[id(node)] = (aval(known[node.name]),)
            else:
                env[id(node)] = None
                missing.append(node)
            continue
        ins = []
        unknown = False
        for src, idx in node.inputs:
            e = env[id(src)]
            if e is None:
                unknown = True
                break
            ins.append(e[idx])
        if unknown:
            solved = _solve_param_shapes(node, env)
            if solved:
                ins = []
                for src, idx in node.inputs:
                    e = env[id(src)]
                    ins.append(e[idx])
                unknown = False
            elif partial:
                env[id(node)] = None
                continue
            else:
                bad = [src.name for src, _ in node.inputs if env[id(src)] is None]
                raise MXNetError(
                    f"infer_shape: cannot determine shape of {bad} for op "
                    f"{node.name}; provide them (reference InferShape errors "
                    f"the same way)")
        params = dict(node.attrs)
        if node.op.mode_dependent:
            params["_train"] = False
        if node.op.dynamic_params:
            for pname in node.op.dynamic_params:
                ins.append(aval((), _np.float32))
                params.pop(pname)
        if node.op.needs_rng:
            ins.append(jax.ShapeDtypeStruct((2,), _np.uint32))
        try:
            out = jax.eval_shape(lambda *xs: node.op.fn(params, *xs), *ins)
        except Exception as e:
            raise MXNetError(f"infer_shape failed at {node.op.name} "
                             f"'{node.name}': {e}") from e
        if not isinstance(out, (tuple, list)):
            out = (out,)
        env[id(node)] = tuple(out[:node.op.num_outputs(params)])

    result = {}
    for n in topo:
        if n.is_variable and env.get(id(n)) is not None:
            result[n.name] = tuple(env[id(n)][0].shape)
    out_shapes = []
    for node, idx in symbol._entries:
        e = env.get(id(node))
        out_shapes.append(tuple(e[idx].shape) if e else None)
    return result, out_shapes, None


def _solve_subgraph_shapes(node, env):
    """Shape inference THROUGH control-flow subgraphs: run the subgraph's
    own inference with the shapes known at the node's inputs (data slices
    lose their scan axis), then write solved closure/state variable shapes
    back to the outer graph — the reference does the equivalent via each
    control-flow op's InferShape recursing into its CachedOp subgraph
    (`src/operator/control_flow.cc` ForeachShape/WhileLoopShape)."""
    import jax
    from ..ops import control_flow as _cf
    p = node.attrs
    op_name = node.op.name
    ins = node.inputs

    def in_shape(idx):
        src, oi = ins[idx]
        e = env[id(src)]
        return None if e is None else tuple(e[oi].shape)

    if op_name == "_foreach":
        nd_, ns = int(p["num_data"]), int(p["num_states"])

        def slot_index(tag):
            k, i = tag[0], int(tag[1:])
            return i if k == "d" else nd_ + i if k == "s" else nd_ + ns + i
        graphs = [(p["subgraph"], p["arg_map"])]
    elif op_name == "_while_loop":
        nv = int(p["num_vars"])

        def slot_index(tag):
            k, i = tag[0], int(tag[1:])
            return i if k == "v" else nv + i
        graphs = [(p["cond_subgraph"], p["cond_arg_map"]),
                  (p["func_subgraph"], p["func_arg_map"])]
    else:  # _cond

        def slot_index(tag):
            return 1 + int(tag[1:])
        graphs = [(p["then_subgraph"], p["then_arg_map"]),
                  (p["else_subgraph"], p["else_arg_map"])]

    for gjson, amap in graphs:
        sub = _cf._subgraph(_cf._json_str(gjson))
        known = {}
        for name, tag in amap:
            shp = in_shape(slot_index(tag))
            if shp is not None:
                known[name] = shp[1:] if (op_name == "_foreach" and
                                          tag[0] == "d") else shp
        try:
            solved, _, _ = _infer_graph(sub, known, True)
        except MXNetError:
            continue
        for name, tag in amap:
            if name in solved and solved[name] and \
                    all(dim > 0 for dim in solved[name]):
                src, _ = ins[slot_index(tag)]
                if src.is_variable and env[id(src)] is None:
                    env[id(src)] = (jax.ShapeDtypeStruct(
                        tuple(solved[name]), _np.float32),)
    return all(env[id(src)] is not None for src, _ in ins)


def _solve_param_shapes(node, env):
    """Infer unbound parameter-variable shapes from op attrs + known data shape
    (the reference does this through each op's InferShape; we encode the rules
    for the parameterized layers)."""
    import jax
    op_name = node.op.name
    ins = node.inputs

    if op_name in ("_foreach", "_while_loop", "_cond"):
        return _solve_subgraph_shapes(node, env)

    def dshape():
        e = env[id(ins[0][0])]
        return None if e is None else tuple(e[ins[0][1]].shape)

    def setvar(i, shape, dtype=_np.float32):
        src, _ = ins[i]
        if src.is_variable and env[id(src)] is None:
            env[id(src)] = (jax.ShapeDtypeStruct(tuple(shape), dtype),)

    d = dshape()
    if d is None:
        return False
    p = node.attrs
    if op_name in ("FullyConnected", "_sg_pallas_fc_relu"):
        num_hidden = int(p["num_hidden"])
        in_units = 1
        if p.get("flatten", True):
            for s in d[1:]:
                in_units *= s
        else:
            in_units = d[-1]
        setvar(1, (num_hidden, in_units))
        if not p.get("no_bias"):
            setvar(2, (num_hidden,))
    elif op_name == "Convolution":
        nf = int(p["num_filter"])
        g = int(p.get("num_group", 1))
        kernel = tuple(p["kernel"])
        setvar(1, (nf, d[1] // g) + kernel)
        if not p.get("no_bias"):
            setvar(2, (nf,))
    elif op_name == "_contrib_quantized_conv":
        nf = int(p["num_filter"])
        g = int(p.get("num_group", 1))
        kernel = tuple(p["kernel"])
        setvar(1, (nf, d[1] // g) + kernel, _np.int8)
        first_minmax = 2
        if not p.get("no_bias"):
            setvar(2, (nf,), _np.int8)
            first_minmax = 3
        for i in range(first_minmax, len(ins)):
            setvar(i, (1,))
    elif op_name == "_contrib_quantized_fully_connected":
        num_hidden = int(p["num_hidden"])
        in_units = 1
        if p.get("flatten", True):
            for s in d[1:]:
                in_units *= s
        else:
            in_units = d[-1]
        setvar(1, (num_hidden, in_units), _np.int8)
        first_minmax = 2
        if not p.get("no_bias"):
            setvar(2, (num_hidden,), _np.int8)
            first_minmax = 3
        for i in range(first_minmax, len(ins)):
            setvar(i, (1,))
    elif op_name == "Deconvolution":
        nf = int(p["num_filter"])
        g = int(p.get("num_group", 1))
        kernel = tuple(p["kernel"])
        setvar(1, (d[1], nf // g) + kernel)
        if not p.get("no_bias"):
            setvar(2, (nf,))
    elif op_name in ("BatchNorm", "BatchNorm_v1", "_contrib_SyncBatchNorm"):
        c = d[int(p.get("axis", 1)) % len(d)]
        for i in range(1, 5):
            setvar(i, (c,))
    elif op_name == "_contrib_DeformableConvolution":
        # inputs: data, offset, weight[, bias]
        nf = int(p["num_filter"])
        g = int(p.get("num_group", 1))
        kernel = tuple(p["kernel"])
        setvar(2, (nf, d[1] // g) + kernel)
        if not p.get("no_bias"):
            setvar(3, (nf,))
    elif op_name == "LayerNorm":
        c = d[int(p.get("axis", -1)) % len(d)]
        setvar(1, (c,))
        setvar(2, (c,))
    elif op_name == "InstanceNorm":
        setvar(1, (d[1],))
        setvar(2, (d[1],))
    elif op_name == "Embedding":
        setvar(1, (int(p["input_dim"]), int(p["output_dim"])))
    elif op_name == "LeakyReLU" and p.get("act_type") == "prelu" and len(ins) > 1:
        setvar(1, (d[1],))
    elif op_name == "RNN":
        from ..ops.nn import rnn_param_size
        H = int(p["state_size"])
        L = int(p["num_layers"])
        bi = bool(p.get("bidirectional"))
        dcount = 2 if bi else 1
        setvar(1, (rnn_param_size(p["mode"], d[2], H, L, bi),))
        setvar(2, (L * dcount, d[1], H))
        if p["mode"] == "lstm" and len(ins) > 3:
            setvar(3, (L * dcount, d[1], H))
    elif op_name in ("SoftmaxOutput", "Softmax", "LinearRegressionOutput",
                     "LogisticRegressionOutput", "MAERegressionOutput",
                     "SVMOutput"):
        if op_name in ("SoftmaxOutput", "Softmax"):
            if p.get("multi_output"):
                setvar(1, (d[0],) + tuple(d[2:]))
            else:
                setvar(1, tuple(d[:-1]))
        elif op_name == "SVMOutput":
            setvar(1, (d[0],))
        else:
            setvar(1, d)
    else:
        return False
    return all(env[id(src)] is not None for src, _ in ins)


def _infer_graph_types(symbol, dtypes):
    known = dict(dtypes)
    out = {}
    for n in symbol._topo():
        if n.is_variable:
            out[n.name] = _np.dtype(known.get(n.name, _np.float32))
    return out
