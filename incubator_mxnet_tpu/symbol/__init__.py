"""`mx.sym` — symbolic graph package (reference `python/mxnet/symbol/`)."""
from .symbol import Symbol, Variable, var, Group, load, load_json
from . import register as _register
import sys as _sys

_register.populate(_sys.modules[__name__])

from . import contrib  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import random  # noqa: E402,F401
