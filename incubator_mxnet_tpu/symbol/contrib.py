"""`mx.sym.contrib` (reference `python/mxnet/symbol/contrib.py`).

Symbolic control flow (`foreach`/`while_loop`/`cond`) traces python callables
over Symbols — the graph executor lowers the resulting subgraphs through
`lax.scan`/`while_loop`/`cond` when compiled (reference
`src/operator/control_flow.cc` runs them as CachedOp subgraphs)."""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _reg
from .symbol import Symbol, _sym_apply

_this = _sys.modules[__name__]
for _name in _reg.list_ops():
    if _name.startswith("_contrib_"):
        def _make(op_name):
            def fn(*args, **kwargs):
                data = [a for a in args if isinstance(a, Symbol)]
                return _sym_apply(op_name, data, kwargs)
            fn.__name__ = op_name[len("_contrib_"):]
            return fn
        setattr(_this, _name[len("_contrib_"):], _make(_name))


# ---------------------------------------------------------------------------
# Symbolic control flow: build `_foreach` / `_while_loop` / `_cond` nodes
# (reference `python/mxnet/symbol/contrib.py:215,378,601`).  The loop body
# is traced ONCE over fresh Variables; every other variable (or computed
# symbol) the body captures becomes a closure input of the node, and the
# subgraph ships in the attrs as symbol JSON (ops/control_flow.py lowers it
# to lax.scan / masked-scan / lax.cond at compile time).
# ---------------------------------------------------------------------------

from .symbol import Variable as _Variable, Group as _Group
from ..base import MXNetError as _MXNetError

_cf_uid = [0]


def _uid():
    _cf_uid[0] += 1
    return _cf_uid[0]


def _flatten(args):
    if isinstance(args, (list, tuple)):
        flat, fmts = [], []
        for a in args:
            f, fmt = _flatten(a)
            flat.extend(f)
            fmts.append(fmt)
        return flat, fmts
    return [args], None


def _regroup(flat, fmt, pos=0):
    if fmt is None:
        return flat[pos], pos + 1
    out = []
    for f in fmt:
        v, pos = _regroup(flat, f, pos)
        out.append(v)
    return out, pos


def _classify_args(sub, mine, seen=None, closures=None):
    """arg_map entries + closure input symbols for a built subgraph.

    `mine` maps id(variable node) -> slot tag for the Variables created to
    stand in for loop slices/states.  Every OTHER variable leaf is a
    closure input — shared BY NODE with the outer graph, so composition
    into the enclosing Symbol works exactly like any other op input.
    Pass `seen`/`closures` to share one closure pool across several
    subgraphs (while_loop's cond+func, cond's then+else)."""
    if sub.list_auxiliary_states():
        raise _MXNetError(
            "control-flow bodies may not contain layers with auxiliary "
            "states (e.g. BatchNorm running stats); keep them outside "
            "the loop")
    arg_map = []
    closure_syms = closures if closures is not None else []
    seen = seen if seen is not None else {}
    for node in sub._topo():
        if not node.is_variable:
            continue
        tag = mine.get(id(node))
        if tag is None:
            j = seen.get(id(node))
            if j is None:
                j = len(seen)
                seen[id(node)] = j
                closure_syms.append(Symbol([(node, 0)]))
            arg_map.append((node.name, f"c{j}"))
        else:
            arg_map.append((node.name, tag))
    return arg_map, closure_syms, seen


def foreach(body, data, init_states, name="foreach"):
    """Symbolic foreach -> ONE `lax.scan` in the compiled program
    (reference `symbol/contrib.py:215` building `_foreach`)."""
    uname = f"{name}{_uid()}"
    data_list, data_fmt = _flatten(data)
    states_list, state_fmt = _flatten(init_states)
    data_vars = [_Variable(f"{uname}_d{i}") for i in range(len(data_list))]
    state_vars = [_Variable(f"{uname}_s{i}") for i in range(len(states_list))]
    d_in, _ = _regroup(data_vars, data_fmt)
    s_in, _ = _regroup(state_vars, state_fmt)
    outs, new_states = body(d_in, s_in)
    outs_list, out_fmt = _flatten(outs)
    new_list, _ = _flatten(new_states)
    if len(new_list) != len(states_list):
        raise _MXNetError(
            f"foreach body returned {len(new_list)} states, expected "
            f"{len(states_list)}")
    sub = _Group(list(outs_list) + list(new_list))
    mine = {id(v._entries[0][0]): f"d{i}" for i, v in enumerate(data_vars)}
    mine.update({id(v._entries[0][0]): f"s{i}"
                 for i, v in enumerate(state_vars)})
    arg_map, closure_syms, _ = _classify_args(sub, mine)
    res = _sym_apply("_foreach", list(data_list) + list(states_list) +
                     closure_syms,
                     {"subgraph": sub.tojson(),
                      "arg_map": tuple(arg_map),
                      "num_data": len(data_list),
                      "num_states": len(states_list),
                      "num_out_data": len(outs_list),
                      "name": uname})
    n_out = len(outs_list)
    outs_r, _ = _regroup([res[i] for i in range(n_out)], out_fmt)
    states_r, _ = _regroup([res[n_out + i] for i in range(len(states_list))],
                           state_fmt)
    return outs_r, states_r


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Symbolic while_loop -> masked `lax.scan` over max_iterations
    (reference `symbol/contrib.py:378` building `_while_loop`; like the
    reference symbolic op, max_iterations is required and outputs are
    padded to it)."""
    if max_iterations is None:
        raise _MXNetError("while_loop: max_iterations is required in the "
                          "symbolic form (static shapes)")
    uname = f"{name}{_uid()}"
    vars_list, var_fmt = _flatten(loop_vars)
    var_syms = [_Variable(f"{uname}_v{i}") for i in range(len(vars_list))]
    v_in, _ = _regroup(var_syms, var_fmt)
    call_args = v_in if isinstance(v_in, list) else [v_in]
    cond_out = cond(*call_args)
    outs, new_vars = func(*call_args)
    outs_list, out_fmt = _flatten(outs)
    new_list, _ = _flatten(new_vars)
    if len(new_list) != len(vars_list):
        raise _MXNetError(
            f"while_loop func returned {len(new_list)} loop_vars, expected "
            f"{len(vars_list)}")
    cond_sub = _Group([cond_out])
    func_sub = _Group(list(outs_list) + list(new_list))
    mine = {id(v._entries[0][0]): f"v{i}" for i, v in enumerate(var_syms)}
    # one closure pool shared by the cond and func graphs
    c_map, closures, seen = _classify_args(cond_sub, mine)
    f_map, closures, seen = _classify_args(func_sub, mine, seen, closures)
    res = _sym_apply("_while_loop", list(vars_list) + closures,
                     {"cond_subgraph": cond_sub.tojson(),
                      "func_subgraph": func_sub.tojson(),
                      "cond_arg_map": tuple(c_map),
                      "func_arg_map": tuple(f_map),
                      "num_vars": len(vars_list),
                      "num_out_data": len(outs_list),
                      "max_iterations": int(max_iterations),
                      "name": uname})
    n_out = len(outs_list)
    outs_r, _ = _regroup([res[i] for i in range(n_out)], out_fmt)
    vars_r, _ = _regroup([res[n_out + i] for i in range(len(vars_list))],
                         var_fmt)
    return outs_r, vars_r


def cond(pred, then_func, else_func, name="cond"):
    """Symbolic cond -> `lax.cond` (reference `symbol/contrib.py:601`
    building `_cond`); the predicate never leaves the device."""
    uname = f"{name}{_uid()}"
    then_out = then_func()
    else_out = else_func()
    t_list, t_fmt = _flatten(then_out)
    e_list, _ = _flatten(else_out)
    if len(t_list) != len(e_list):
        raise _MXNetError(
            f"cond branches must produce the same number of outputs "
            f"({len(t_list)} vs {len(e_list)})")
    t_sub = _Group(list(t_list))
    e_sub = _Group(list(e_list))
    # one closure pool shared by the then and else graphs
    t_map, closures, seen = _classify_args(t_sub, {})
    e_map, closures, seen = _classify_args(e_sub, {}, seen, closures)
    res = _sym_apply("_cond", [pred] + closures,
                     {"then_subgraph": t_sub.tojson(),
                      "else_subgraph": e_sub.tojson(),
                      "then_arg_map": tuple(t_map),
                      "else_arg_map": tuple(e_map),
                      "num_outputs": len(t_list),
                      "name": uname})
    outs_r, _ = _regroup([res[i] for i in range(len(t_list))], t_fmt)
    return outs_r


def foreach_unroll(step, inputs, begin_state, layout, length):
    """One-scan unroll shared by the RNN cell packages (gluon + legacy):
    swap the sequence T-major, slice to `length` (bind errors when the
    data is shorter, like a static split would), run `step(x, states)`
    under foreach, swap back."""
    from .. import symbol as sym_mod
    axis = layout.find("T")
    seq = inputs if axis == 0 else \
        sym_mod.swapaxes(inputs, dim1=0, dim2=axis)
    seq = sym_mod.slice_axis(seq, axis=0, begin=0, end=int(length))
    outs, states = foreach(step, seq, begin_state)
    if axis != 0:
        outs = sym_mod.swapaxes(outs, dim1=0, dim2=axis)
    return outs, states
