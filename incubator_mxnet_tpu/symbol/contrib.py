"""`mx.sym.contrib` (reference `python/mxnet/symbol/contrib.py`).

Symbolic control flow (`foreach`/`while_loop`/`cond`) traces python callables
over Symbols — the graph executor lowers the resulting subgraphs through
`lax.scan`/`while_loop`/`cond` when compiled (reference
`src/operator/control_flow.cc` runs them as CachedOp subgraphs)."""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _reg
from .symbol import Symbol, _sym_apply

_this = _sys.modules[__name__]
for _name in _reg.list_ops():
    if _name.startswith("_contrib_"):
        def _make(op_name):
            def fn(*args, **kwargs):
                data = [a for a in args if isinstance(a, Symbol)]
                return _sym_apply(op_name, data, kwargs)
            fn.__name__ = op_name[len("_contrib_"):]
            return fn
        setattr(_this, _name[len("_contrib_"):], _make(_name))
