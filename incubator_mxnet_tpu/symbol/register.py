"""Generate `sym.*` op functions from the registry (reference
`python/mxnet/symbol/register.py`)."""
from __future__ import annotations

import sys
import types

from ..ops import registry as _reg
from .symbol import Symbol, _sym_apply

_internal = types.ModuleType("incubator_mxnet_tpu.symbol._internal")
sys.modules["incubator_mxnet_tpu.symbol._internal"] = _internal


def _make_function(op, public_name):
    def fn(*args, **kwargs):
        data = []
        for a in args:
            if isinstance(a, Symbol):
                data.append(a)
            elif isinstance(a, (list, tuple)) and all(
                    isinstance(x, Symbol) for x in a):
                data.extend(a)
            else:
                raise TypeError(
                    f"Operator {op.name}: symbolic inputs must be Symbol, "
                    f"got {type(a).__name__}")
        # symbols may also arrive as kwargs (sym op(data=x, weight=w))
        sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        for k in sym_kwargs:
            kwargs.pop(k)
        if sym_kwargs and not data:
            order = ["data", "lhs", "rhs", "weight", "bias", "gamma", "beta",
                     "moving_mean", "moving_var", "label", "indices", "grid",
                     "parameters", "state", "state_cell"]
            for k in order:
                if k in sym_kwargs:
                    data.append(sym_kwargs.pop(k))
            data.extend(sym_kwargs.values())
        elif sym_kwargs:
            data.extend(sym_kwargs.values())
        return _sym_apply(op.name, data, kwargs)

    fn.__name__ = public_name
    fn.__doc__ = op.doc or f"TPU-native symbolic operator `{op.name}`."
    return fn


def populate(target_module):
    seen = set()
    for name in _reg.list_ops():
        op = _reg.get(name)
        seen.add(id(op))
        f = _make_function(op, name)
        setattr(_internal, name, f)
        if not name.startswith("_") and not hasattr(target_module, name):
            setattr(target_module, name, f)
    target_module._internal = _internal
