"""`mx.sym.linalg` (reference `python/mxnet/symbol/linalg.py`)."""
from .symbol import _sym_apply


def _wrap(opname):
    def fn(*args, **kwargs):
        return _sym_apply(opname, list(args), kwargs)
    fn.__name__ = opname.replace("linalg_", "")
    return fn


gemm = _wrap("linalg_gemm")
gemm2 = _wrap("linalg_gemm2")
potrf = _wrap("linalg_potrf")
potri = _wrap("linalg_potri")
trsm = _wrap("linalg_trsm")
trmm = _wrap("linalg_trmm")
syrk = _wrap("linalg_syrk")
gelqf = _wrap("linalg_gelqf")
syevd = _wrap("linalg_syevd")
sumlogdiag = _wrap("linalg_sumlogdiag")
extractdiag = _wrap("linalg_extractdiag")
makediag = _wrap("linalg_makediag")
inverse = _wrap("linalg_inverse")
det = _wrap("linalg_det")
