"""mxshard: static SPMD sharding analyzer (GSPMD-style propagation).

The dynamic half of the sharding story already exists — megatron rules
shard the TransformerLM (`parallel/tensor_parallel.py`), the pod fast
path exchanges gradients over the dp axis — but a mis-sharded param is
only discovered at run time: it silently replicates (per-device HBM
blowup) or GSPMD inserts a hidden all-gather that the mxcost collective
enumerator never models (cost.py only understands the dp bucket psum
plan).  mxshard closes that gap statically, before anything compiles:

* **propagation** — given a Symbol graph (or traced jaxpr), a mesh
  spec (`"dp=2,tp=2"` / axis dict / `jax.sharding.Mesh`) and a
  `ShardingRules` set, PartitionSpecs are seeded on the variables
  (step inputs ride the dp axis on dim 0, params get their rule's
  spec) and propagated forward through every op.  Dot-class ops carry
  the megatron algebra (column-parallel → output-dim sharded,
  row-parallel → contraction over a sharded dim → psum), embedding
  lookups over a vocab-sharded table psum, reduces over sharded dims
  psum, reshape/transpose/slice remap specs dimension-wise, and a
  dot-class handler back-infers the spec its operands need (the
  "backward" half: bias of a column-parallel FC is sliced, an
  activation feeding a row-parallel FC must arrive contraction-
  sharded).  An op with no handler falls back to **replicated
  outputs** and the fallback is recorded (`shard-fallback`) instead of
  silently propagating fiction.
* **findings** — `implicit-replication` (param/activation ≥
  `MXNET_SHARD_MIN_MB` fully replicated while a >1-device non-batch
  axis exists), `hidden-reshard` (an edge whose producer spec differs
  from what the consumer needs, classified all-gather / all-to-all /
  slice with statically computed bytes, naming both nodes),
  `rule-coverage` (a param matching zero or ≥2 rules of a rule set
  that clearly applies to the model — the static twin of the dynamic
  test_llm coverage test), and `dp-axis-leak` (a batch-led activation
  whose dim-0 dp sharding an op dropped past the input).
* **costs** — per-DEVICE peak HBM from sharded avals (the same
  liveness walk as `cost._liveness_pass`, buffer sizes divided by
  their shard counts), and the collective enumerator grows tp/GSPMD
  collectives alongside the dp bucket plan: `shard_collectives`
  returns the dp exchange (the SAME `kvstore.plan_buckets` rule —
  byte-exact against measured `KVStore.stats()` / `pod_stats`) plus
  the statically derived tp psums/reshards with per-collective ICI
  bytes (ring model, matching cost.py: all-reduce moves
  ``2*(n-1)/n * bytes`` per chip, all-gather ``(n-1)/n * bytes``).

Surfaced via `tools/mxlint.py --shard-report` (budget-gated against
COST_BUDGETS.json's ``sharding`` section) and the `run_tpu_parity`
sharding stage.  Findings are plain `analysis.findings` currency; every
code registers in CODE_TABLE.
"""
from __future__ import annotations

import numpy as np

from .findings import Finding, Report, ERROR, WARN, HINT
from .cost import _aval_bytes, DOT_CLASS

# every finding code this module emits (tests/test_analysis.py folds
# this into the no-orphan CODE_TABLE check)
CODES = ("implicit-replication", "hidden-reshard", "rule-coverage",
         "dp-axis-leak", "shard-fallback", "shard-summary")

_MB = float(1 << 20)

# default step-input heuristic shared with cost._liveness_pass
_STEP_INPUT_HINTS = ("data", "_label", "state")


# ---------------------------------------------------------------------------
# mesh / spec plumbing.  A spec is a plain tuple, one entry per tensor
# dim: a mesh-axis name (str) or None (replicated on that dim).
# ---------------------------------------------------------------------------

def _mesh_axes(mesh):
    """Normalize a mesh argument to ``{axis_name: size}``.

    Accepts a spec string (``"dp=2,tp=2"``, the `parallel.mesh`
    grammar), a dict, or anything with a Mesh-like ``.shape`` mapping.
    """
    if mesh is None:
        return {}
    if isinstance(mesh, str):
        from ..parallel.mesh import parse_spec
        return parse_spec(mesh)
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)
    if shape is not None:
        return {str(k): int(v) for k, v in dict(shape).items()}
    raise TypeError(f"cannot derive mesh axes from {mesh!r}")


def _axis_size(ax, axes):
    if ax is None:
        return 1
    if isinstance(ax, (tuple, list)):
        n = 1
        for a in ax:
            n *= int(axes.get(str(a), 1))
        return n
    return int(axes.get(str(ax), 1))


def _spec_tuple(spec, ndim):
    """PartitionSpec / tuple / list -> padded plain tuple of len ndim."""
    entries = tuple(spec) if spec is not None else ()
    entries = entries[:ndim] + (None,) * (ndim - len(entries))
    return tuple(e if (e is None or isinstance(e, (tuple, list)))
                 else str(e) for e in entries)


def _clamp_spec(spec, shape, axes):
    """Drop spec axes absent from the mesh, of size 1, or that don't
    divide their dim — the same forgiveness `shard_params` applies."""
    out = []
    for dim, ax in zip(shape, _spec_tuple(spec, len(shape))):
        n = _axis_size(ax, axes)
        out.append(ax if (ax is not None and n > 1 and dim % n == 0)
                   else None)
    return tuple(out)


def _nshards(spec, axes):
    n = 1
    for ax in spec:
        n *= _axis_size(ax, axes)
    return n


def _sharded_bytes(aval, spec, axes):
    if aval is None:
        return 0
    return _aval_bytes(aval) // max(1, _nshards(spec, axes))


def _fmt_spec(spec):
    return "P(" + ", ".join("None" if a is None else repr(a)
                            for a in spec) + ")"


def _classify_reshard(src_spec, dst_spec):
    src_sh = any(a is not None for a in src_spec)
    dst_sh = any(a is not None for a in dst_spec)
    if src_sh and dst_sh:
        return "all-to-all"
    if src_sh:
        return "all-gather"
    return "slice"


def _reshard_ici_bytes(kind, full_bytes, n):
    """Per-chip ICI bytes for one reshard (ring model, n shards)."""
    if n <= 1:
        return 0
    if kind == "all-gather":
        return int(full_bytes * (n - 1) // n)
    if kind == "all-to-all":
        return int(full_bytes * (n - 1) // (n * n))
    return 0   # slice: drop local data, no wire traffic


# ---------------------------------------------------------------------------
# report currency
# ---------------------------------------------------------------------------

class ShardReport:
    """Everything the propagation derived for one program."""

    def __init__(self, target, axes):
        self.target = target
        self.mesh = dict(axes)
        self.findings = Report(target=target)
        self.specs = {}            # node name -> spec tuple (output 0)
        self.reshards = []         # [{src, dst, kind, bytes, ici_bytes}]
        self.collectives = []      # [{node, op, kind, axis, bytes, ici_bytes}]
        self.fallback_ops = {}     # op name -> node count
        self.per_device_peak_hbm_bytes = None
        self.replicated_peak_hbm_bytes = None

    @property
    def ici_bytes_per_step(self):
        """tp/GSPMD ICI bytes per chip per step (dp plan excluded —
        `shard_collectives` folds that in)."""
        return int(sum(c["ici_bytes"] for c in self.collectives) +
                   sum(r["ici_bytes"] for r in self.reshards))

    def as_dict(self):
        return {
            "target": self.target,
            "mesh": dict(self.mesh),
            "per_device_peak_hbm_bytes": self.per_device_peak_hbm_bytes,
            "replicated_peak_hbm_bytes": self.replicated_peak_hbm_bytes,
            "tp_collectives_per_step": len(self.collectives),
            "tp_ici_bytes_per_step": self.ici_bytes_per_step,
            "reshard_edges": len(self.reshards),
            "fallback_ops": dict(self.fallback_ops),
            "findings": [f.as_dict() for f in self.findings],
        }


# ---------------------------------------------------------------------------
# rule coverage — the static twin of test_llm's dynamic megatron check
# ---------------------------------------------------------------------------

def check_rule_coverage(param_shapes, rules, target=None, report=None):
    """Check a `ShardingRules` set against a model's parameter names.

    ``param_shapes``: {name: shape tuple or None}.  A param matching
    >=2 rule entries is ambiguous (first-match-wins hides the loser); a
    matrix param (ndim>=2) matching ZERO rules silently replicates.
    1-D params (biases, norm scales) are allowed to fall through to
    the replicated default.  If NO param matches ANY rule the set is
    considered not-applicable to this model and nothing is emitted
    (a convnet analyzed under megatron rules is not a coverage gap).
    """
    rep = report if report is not None else Report(target=target)
    matched = {name: [prog.pattern for prog, _ in rules.rules
                      if prog.search(name)]
               for name in param_shapes}
    if not any(matched.values()):
        return rep
    for name in sorted(matched):
        pats = matched[name]
        shape = param_shapes[name]
        ndim = len(shape) if shape is not None else 0
        if len(pats) >= 2:
            rep.add(Finding(
                "shard.rules", "rule-coverage", ERROR,
                f"param '{name}' matches {len(pats)} sharding rules "
                f"({', '.join(repr(p) for p in pats)}); first-match-wins "
                f"silently ignores the rest — tighten the regexes",
                node=name))
        elif not pats and ndim >= 2:
            rep.add(Finding(
                "shard.rules", "rule-coverage", ERROR,
                f"param '{name}' {tuple(shape) if shape else ''} matches "
                f"no sharding rule; it will silently replicate on every "
                f"device", node=name))
    return rep


# ---------------------------------------------------------------------------
# the propagation pass
# ---------------------------------------------------------------------------

# single-input ops whose output spec is the input spec (shape-preserving)
_PASS_THROUGH = frozenset([
    "Activation", "LeakyReLU", "Dropout", "Cast", "clip", "relu",
    "sigmoid", "tanh", "exp", "log", "sqrt", "square", "negative",
    "abs", "erf", "softsign", "identity", "_copy", "BlockGrad",
    "stop_gradient", "L2Normalization",
])

# single-input ops where a dim keeps its spec iff its SIZE is unchanged
# (pooling/padding change spatial dims but never batch/channel)
_SIZE_ALIGNED = frozenset([
    "Pooling", "UpSampling", "pad", "Pad", "slice", "slice_like",
    "Crop", "BilinearSampler", "_contrib_quantized_pooling",
])

# quantize/dequantize keep the data layout; the min/max outputs are
# replicated scalars
_QUANT_PASS = frozenset([
    "_contrib_quantize", "_contrib_quantize_v2", "_contrib_dequantize",
    "_contrib_requantize",
])

# multi-input elementwise/broadcast families -> dimension-wise join
_ELEMWISE_PREFIXES = ("broadcast_", "elemwise_", "_plus", "_minus",
                      "_mul", "_div", "_maximum", "_minimum", "_power")
_ELEMWISE = frozenset(["add_n", "where", "maximum", "minimum", "hypot"])

_REDUCE_OPS = frozenset(["sum", "mean", "max", "min", "prod", "nansum",
                         "nanprod", "norm", "argmax", "argmin"])


def _is_elemwise(opname):
    return opname in _ELEMWISE or \
        any(opname.startswith(p) for p in _ELEMWISE_PREFIXES)


def analyze_sharding(symbol, shapes=None, mesh="dp=8", rules=None,
                     dtypes=None, batch_axis="dp", step_inputs=None,
                     min_mb=None, name=None):
    """Propagate PartitionSpecs through a Symbol graph; return a
    `ShardReport` (findings + specs + reshards + tp collectives +
    per-device peak HBM).  Pure analysis: no devices touched, nothing
    compiled."""
    from . import graph_passes as gp
    from .. import config as _config

    axes = _mesh_axes(mesh)
    if min_mb is None:
        min_mb = float(_config.get("MXNET_SHARD_MIN_MB"))
    min_bytes = int(min_mb * _MB)
    topo = symbol._topo()
    env = gp._abstract_env(symbol, shapes, dtypes)
    rep = ShardReport(name or "symbol", axes)

    if step_inputs is None:
        step_inputs = {n.name for n in topo if n.is_variable and
                       (n.name.startswith("data") or
                        n.name.endswith("_label") or
                        "state" in n.name)}
    else:
        step_inputs = set(step_inputs)

    def avals_of(node):
        return env.get(id(node)) or (None,) * node.num_outputs()

    # ---- rule coverage (independent of propagation) --------------------
    if rules is not None:
        param_shapes = {}
        for n in topo:
            if not n.is_variable or n.name in step_inputs:
                continue
            a = avals_of(n)[0]
            param_shapes[n.name] = tuple(a.shape) if a is not None else \
                n._extra_attrs.get("__shape__")
        check_rule_coverage(param_shapes, rules, report=rep.findings)

    # ---- seed variable specs ------------------------------------------
    specs = {}   # id(node) -> tuple(spec per output)
    dp_size = _axis_size(batch_axis, axes)
    batch_size = None
    for n in topo:
        if not n.is_variable:
            continue
        a = avals_of(n)[0]
        ndim = len(a.shape) if a is not None else 0
        if n.name in step_inputs and ndim:
            sp = (batch_axis,) + (None,) * (ndim - 1)
            if batch_size is None and n.name.startswith("data"):
                batch_size = a.shape[0] if a is not None else None
        elif rules is not None and ndim:
            sp = _spec_tuple(rules.spec_for(n.name), ndim)
        else:
            sp = (None,) * ndim
        if a is not None:
            sp = _clamp_spec(sp, a.shape, axes)
        specs[id(n)] = (sp,)
        rep.specs[n.name] = sp

    def spec_of(src, idx):
        got = specs.get(id(src))
        if got is None or idx >= len(got):
            a = avals_of(src)[idx] if idx < len(avals_of(src)) else None
            return (None,) * (len(a.shape) if a is not None else 0)
        return got[idx]

    # ---- recording helpers --------------------------------------------
    def record_reshard(node, src, have, want, aval, why=""):
        """An edge whose producer spec differs from what the consumer
        needs: classify, cost, and (if big enough) surface."""
        if have == want or aval is None:
            return
        kind = _classify_reshard(have, want)
        full = _aval_bytes(aval)
        n = max(_nshards(have, axes), _nshards(want, axes))
        ici = _reshard_ici_bytes(kind, full, n)
        rep.reshards.append({
            "src": src.name, "dst": node.name, "kind": kind,
            "bytes": full, "ici_bytes": ici,
            "from": _fmt_spec(have), "to": _fmt_spec(want)})
        if full >= min_bytes:
            msg = (f"edge {src.name} -> {node.name}: producer spec "
                   f"{_fmt_spec(have)} != consumer spec {_fmt_spec(want)} "
                   f"— GSPMD inserts a hidden {kind} moving {full} bytes")
            if why:
                msg += f" ({why})"
            rep.findings.add(Finding("shard.propagate", "hidden-reshard",
                                     WARN, msg, node=node.name))

    def record_psum(node, ax, out_aval, out_spec, opname):
        """Contraction/reduction over a sharded axis -> all-reduce."""
        n = _axis_size(ax, axes)
        if n <= 1 or out_aval is None:
            return
        payload = _sharded_bytes(out_aval, out_spec, axes)
        rep.collectives.append({
            "node": node.name, "op": opname, "kind": "psum",
            "axis": ax if isinstance(ax, str) else str(ax),
            "bytes": payload,
            "ici_bytes": int(2 * (n - 1) * payload // n)})

    def fallback(node, opname, in_specs, in_avals, out_avals):
        """Unknown op: outputs replicate; the claim is recorded, and any
        sharded input is costed as an implied all-gather."""
        rep.fallback_ops[opname] = rep.fallback_ops.get(opname, 0) + 1
        for (src, idx), sp, a in zip(node.inputs, in_specs, in_avals):
            if any(e is not None for e in sp):
                record_reshard(node, src, sp,
                               (None,) * len(sp), a,
                               why=f"no propagation rule for op "
                                   f"'{opname}'; inputs gathered")
        out = []
        for a in out_avals:
            nd = len(a.shape) if a is not None else 0
            out.append((None,) * nd)
        return tuple(out)

    def join_specs(node, in_specs, in_avals, out_aval):
        """Dimension-wise union with trailing-dim broadcast alignment;
        conflicting inputs reshard to the first claimant's axis."""
        nd = len(out_aval.shape)
        out = [None] * nd
        for d in range(nd):
            for sp, a in zip(in_specs, in_avals):
                if a is None:
                    continue
                k = d - (nd - len(a.shape))
                if k < 0 or a.shape[k] != out_aval.shape[d]:
                    continue
                if sp[k] is not None:
                    out[d] = sp[k]
                    break
        out = _clamp_spec(tuple(out), out_aval.shape, axes)
        for (src, idx), sp, a in zip(node.inputs, in_specs, in_avals):
            if a is None or len(a.shape) == 0:
                continue
            off = nd - len(a.shape)
            want = tuple(out[off + k] if a.shape[k] == out_aval.shape[off + k]
                         else None for k in range(len(a.shape)))
            want = _clamp_spec(want, a.shape, axes)
            if sp != want:
                record_reshard(node, src, sp, want, a)
        return tuple(out)

    # ---- the walk ------------------------------------------------------
    for node in topo:
        if node.is_variable:
            continue
        opname = node.op.name
        in_specs = [spec_of(src, idx) for src, idx in node.inputs]
        in_avals = [avals_of(src)[idx] if idx < len(avals_of(src)) else None
                    for src, idx in node.inputs]
        out_avals = avals_of(node)
        out0 = out_avals[0]
        attrs = node.attrs

        out_specs = None
        nd_out = len(out0.shape) if out0 is not None else 0

        if opname in DOT_CLASS and opname in ("FullyConnected",
                                              "_contrib_quantized_fully_connected"):
            x_src, x_idx = node.inputs[0]
            xs, xa = in_specs[0], in_avals[0]
            ws = in_specs[1] if len(in_specs) > 1 else ()
            wa = in_avals[1] if len(in_avals) > 1 else None
            col = ws[0] if len(ws) > 0 else None   # (N, K): N sharded
            row = ws[1] if len(ws) > 1 else None   # (N, K): K sharded
            flatten = bool(attrs.get("flatten", True))
            if xa is not None and flatten and len(xa.shape) > 2 and \
                    any(e is not None for e in xs[1:]):
                # flatten folds dims 1.. into the contraction: any
                # sharding there must gather first
                want = (xs[0],) + (None,) * (len(xs) - 1)
                record_reshard(node, x_src, xs, want, xa,
                               why="flatten folds sharded dims into the "
                                   "contraction")
                xs = want
            batch_spec = tuple(xs[:-1]) if (xa is not None and
                                            len(xa.shape) > 1) else ()
            if flatten and nd_out == 2:
                batch_spec = (xs[0] if xs else None,)
            xk = xs[-1] if xs else None
            if row is not None:
                # row-parallel: contraction over the sharded K — the
                # operand must arrive K-sharded (backward inference),
                # and the partial products psum over the row axis
                want = batch_spec + (row,)
                if xa is not None and xs != want:
                    record_reshard(node, x_src, xs, want, xa,
                                   why="row-parallel contraction needs a "
                                       "K-sharded operand")
                out_spec = batch_spec + (col,)
                out_spec = _clamp_spec(out_spec, out0.shape, axes) \
                    if out0 is not None else out_spec
                record_psum(node, row, out0, out_spec, opname)
            else:
                if xk is not None and xk != row:
                    # contraction sharded on x but not on w: gather x
                    want = batch_spec + (None,)
                    record_reshard(node, x_src, xs, want, xa,
                                   why="contraction dim sharded on the "
                                       "operand but not the weight")
                out_spec = batch_spec + (col,)
                out_spec = _clamp_spec(out_spec, out0.shape, axes) \
                    if out0 is not None else out_spec
            # bias of a column-parallel FC is sliced along the output
            # dim (backward inference) — free, no finding
            out_specs = (out_spec,) + tuple(
                (None,) * len(a.shape) if a is not None else ()
                for a in out_avals[1:])

        elif opname in DOT_CLASS and opname in ("Convolution",
                                                "Deconvolution",
                                                "_contrib_quantized_conv"):
            xs, xa = in_specs[0], in_avals[0]
            ws = in_specs[1] if len(in_specs) > 1 else ()
            x_src, _ = node.inputs[0]
            if xa is not None and any(e is not None for e in xs[1:]):
                want = (xs[0],) + (None,) * (len(xs) - 1)
                record_reshard(node, x_src, xs, want, xa,
                               why="conv contracts channel/spatial dims")
                xs = want
            cout = ws[0] if len(ws) > 0 else None
            if len(ws) > 1 and any(e is not None for e in ws[1:]):
                w_src, _ = node.inputs[1]
                record_reshard(node, w_src, ws,
                               (ws[0],) + (None,) * (len(ws) - 1),
                               in_avals[1],
                               why="conv kernel contraction dims sharded")
            out_spec = ((xs[0] if xs else None, cout) +
                        (None,) * max(0, nd_out - 2))[:nd_out]
            out_spec = _clamp_spec(out_spec, out0.shape, axes) \
                if out0 is not None else tuple(out_spec)
            out_specs = (out_spec,)

        elif opname in DOT_CLASS:   # dot / batch_dot / linalg_gemm*
            xs = in_specs[0] if in_specs else ()
            ys = in_specs[1] if len(in_specs) > 1 else ()
            xk = xs[-1] if xs else None
            yk = ys[0] if ys else None
            out_spec = (tuple(xs[:-1]) + (ys[-1] if ys else None,)) \
                if nd_out else ()
            out_spec = out_spec[:nd_out] + (None,) * (nd_out - len(out_spec))
            out_spec = _clamp_spec(out_spec, out0.shape, axes) \
                if out0 is not None else out_spec
            if xk is not None and xk == yk:
                record_psum(node, xk, out0, out_spec, opname)
            elif xk is not None or yk is not None:
                for (src, idx), sp, a, want_last in (
                        (node.inputs[0], xs, in_avals[0], None),):
                    if sp and sp[-1] is not None:
                        record_reshard(node, src, sp,
                                       tuple(sp[:-1]) + (None,), a,
                                       why="mismatched contraction "
                                           "sharding")
            out_specs = (out_spec,)

        elif opname == "Embedding":
            tok_spec = in_specs[0] if in_specs else ()
            ws = in_specs[1] if len(in_specs) > 1 else ()
            vocab_ax = ws[0] if len(ws) > 0 else None
            feat_ax = ws[1] if len(ws) > 1 else None
            out_spec = tuple(tok_spec) + (feat_ax,)
            out_spec = out_spec[:nd_out] + (None,) * (nd_out - len(out_spec))
            out_spec = _clamp_spec(out_spec, out0.shape, axes) \
                if out0 is not None else out_spec
            if vocab_ax is not None:
                # vocab-sharded table: masked local lookup + psum
                record_psum(node, vocab_ax, out0, out_spec, opname)
            out_specs = (out_spec,)

        elif opname in ("Reshape", "Flatten", "reshape"):
            xs = in_specs[0] if in_specs else ()
            xa = in_avals[0] if in_avals else None
            out = [None] * nd_out
            if xa is not None and out0 is not None and len(xa.shape) and \
                    nd_out:
                in0, o0 = xa.shape[0], out0.shape[0]
                if o0 == in0 or (in0 and o0 % in0 == 0) or \
                        (o0 and in0 % o0 == 0):
                    out[0] = xs[0]   # merge/split keeps dim-0 sharding
                if nd_out > 1 and len(xa.shape) > 1 and \
                        out0.shape[-1] == xa.shape[-1]:
                    out[-1] = xs[-1]
                carried = {e for e in out if e is not None}
                lost = [e for e in xs if e is not None and e not in carried]
                if lost:
                    x_src, _ = node.inputs[0]
                    record_reshard(node, x_src, xs,
                                   tuple(out[:len(xs)]) +
                                   (None,) * max(0, len(xs) - nd_out), xa,
                                   why="reshape folds a sharded dim")
            out_spec = _clamp_spec(tuple(out), out0.shape, axes) \
                if out0 is not None else tuple(out)
            out_specs = (out_spec,)

        elif opname in ("transpose", "Transpose"):
            xs = in_specs[0] if in_specs else ()
            perm = attrs.get("axes") or tuple(reversed(range(len(xs))))
            out_spec = tuple(xs[p] if p < len(xs) else None for p in perm)
            out_specs = (_clamp_spec(out_spec, out0.shape, axes)
                         if out0 is not None else out_spec,)

        elif opname == "slice_axis":
            xs = list(in_specs[0]) if in_specs else []
            xa = in_avals[0] if in_avals else None
            ax = int(attrs.get("axis", 0))
            if xa is not None and ax < 0:
                ax += len(xa.shape)
            if 0 <= ax < len(xs) and xs[ax] is not None:
                x_src, _ = node.inputs[0]
                n = _axis_size(xs[ax], axes)
                if out0 is not None and out0.shape[ax] % n == 0:
                    # the slice re-partitions across the shard group
                    rep.reshards.append({
                        "src": x_src.name, "dst": node.name,
                        "kind": "slice", "bytes": _aval_bytes(out0),
                        "ici_bytes": 0,
                        "from": _fmt_spec(tuple(xs)),
                        "to": _fmt_spec(tuple(xs))})
                else:
                    record_reshard(node, x_src, tuple(xs),
                                   tuple(None if i == ax else e
                                         for i, e in enumerate(xs)), xa,
                                   why="slice boundary does not divide "
                                       "the shard grid")
                    xs[ax] = None
            out_spec = _clamp_spec(tuple(xs), out0.shape, axes) \
                if out0 is not None else tuple(xs)
            out_specs = (out_spec,)

        elif opname in _REDUCE_OPS:
            xs = in_specs[0] if in_specs else ()
            xa = in_avals[0] if in_avals else None
            ax_attr = attrs.get("axis")
            if ax_attr is None:
                reduced = set(range(len(xs)))
            else:
                ax_list = ax_attr if isinstance(ax_attr, (tuple, list)) \
                    else (ax_attr,)
                reduced = {a + len(xs) if a < 0 else a for a in
                           (int(a) for a in ax_list)}
            keepdims = bool(attrs.get("keepdims", False))
            out = []
            for i, e in enumerate(xs):
                if i in reduced:
                    if e is not None:
                        record_psum(node, e, out0,
                                    tuple(x for j, x in enumerate(xs)
                                          if j not in reduced), opname)
                    if keepdims:
                        out.append(None)
                else:
                    out.append(e)
            out_spec = tuple(out)[:nd_out] + \
                (None,) * max(0, nd_out - len(out))
            out_specs = (_clamp_spec(out_spec, out0.shape, axes)
                         if out0 is not None else out_spec,)

        elif opname == "BlockwiseAttention":
            joined = join_specs(node, in_specs, in_avals, out0) \
                if out0 is not None else ()
            out = list(joined)
            if len(out) >= 2 and out[1] is not None:
                # sequence-sharded attention needs ring attention; the
                # static model gathers instead
                q_src, _ = node.inputs[0]
                record_reshard(node, q_src, in_specs[0],
                               tuple(None if i == 1 else e
                                     for i, e in enumerate(in_specs[0])),
                               in_avals[0],
                               why="attention mixes the sequence dim")
                out[1] = None
            out_specs = (tuple(out),)

        elif opname in ("LayerNorm", "InstanceNorm", "L2Normalization",
                        "softmax", "log_softmax", "SoftmaxActivation"):
            xs = list(in_specs[0]) if in_specs else []
            xa = in_avals[0] if in_avals else None
            ax = int(attrs.get("axis", -1))
            if xa is not None and ax < 0:
                ax += len(xa.shape)
            if 0 <= ax < len(xs) and xs[ax] is not None:
                x_src, _ = node.inputs[0]
                record_reshard(node, x_src, tuple(xs),
                               tuple(None if i == ax else e
                                     for i, e in enumerate(xs)), xa,
                               why=f"{opname} normalizes over a sharded "
                                   f"dim")
                xs[ax] = None
            out_specs = tuple([tuple(xs)] +
                              [(None,) * len(a.shape) if a is not None
                               else () for a in out_avals[1:]])

        elif opname in ("SoftmaxOutput", "LinearRegressionOutput",
                        "LogisticRegressionOutput", "MAERegressionOutput",
                        "MakeLoss"):
            xs = list(in_specs[0]) if in_specs else []
            if opname == "SoftmaxOutput" and xs and xs[-1] is not None:
                # softmax normalizes over the class dim: vocab-sharded
                # logits gather first
                x_src, _ = node.inputs[0]
                record_reshard(node, x_src, tuple(xs),
                               tuple(xs[:-1]) + (None,), in_avals[0],
                               why="softmax normalizes over a sharded "
                                   "class dim")
                xs[-1] = None
            out_specs = (tuple(xs),)

        elif opname in ("BatchNorm", "BatchNorm_v1"):
            xs = in_specs[0] if in_specs else ()
            out_specs = tuple([tuple(xs)] +
                              [(None,) * len(a.shape) if a is not None
                               else () for a in out_avals[1:]])

        elif opname in _QUANT_PASS:
            xs = tuple(in_specs[0]) if in_specs else ()
            out_specs = tuple([_clamp_spec(xs, out0.shape, axes)
                               if out0 is not None else xs] +
                              [(None,) * len(a.shape) if a is not None
                               else () for a in out_avals[1:]])

        elif opname in _PASS_THROUGH:
            out_specs = tuple(tuple(in_specs[0]) if in_specs else ()
                              for _ in out_avals)

        elif opname in _SIZE_ALIGNED and in_avals and \
                in_avals[0] is not None and out0 is not None and \
                len(in_avals[0].shape) == nd_out:
            xs, xa = in_specs[0], in_avals[0]
            out_spec = tuple(xs[i] if xa.shape[i] == out0.shape[i] else None
                             for i in range(nd_out))
            out_specs = (_clamp_spec(out_spec, out0.shape, axes),)

        elif _is_elemwise(opname) and out0 is not None:
            out_specs = (join_specs(node, in_specs, in_avals, out0),)

        elif in_avals and in_avals[0] is not None and out0 is not None and \
                in_avals[0].shape == out0.shape and len(node.inputs) == 1:
            # shape-preserving unary op: specs survive
            out_specs = (tuple(in_specs[0]),)

        fell_back = out_specs is None
        if fell_back:
            out_specs = fallback(node, opname, in_specs, in_avals,
                                 out_avals)

        # pad/truncate to the real output count
        out_specs = tuple(out_specs)[:len(out_avals)]
        out_specs = out_specs + tuple(
            (None,) * (len(a.shape) if a is not None else 0)
            for a in out_avals[len(out_specs):])
        specs[id(node)] = out_specs
        rep.specs[node.name] = out_specs[0]

        # ---- dp-axis-leak: a batch-led output lost its dim-0 dp ------
        # (fallback nodes are already flagged shard-fallback; their
        # replication is a modeling upper bound, not a proven leak)
        if not fell_back and \
                dp_size > 1 and batch_size and out0 is not None and \
                len(out0.shape) and out0.shape[0] == batch_size and \
                out_specs[0] and out_specs[0][0] != batch_axis:
            fed_dp = any(sp and sp[0] == batch_axis and a is not None and
                         len(a.shape) and a.shape[0] == batch_size
                         for sp, a in zip(in_specs, in_avals))
            if fed_dp:
                rep.findings.add(Finding(
                    "shard.propagate", "dp-axis-leak", WARN,
                    f"op '{opname}' output is batch-led but dim 0 lost "
                    f"its '{batch_axis}' sharding; every device now "
                    f"computes the full batch downstream",
                    node=node.name))

    # ---- implicit replication -----------------------------------------
    nonbatch = any(sz > 1 for ax, sz in axes.items() if ax != batch_axis)
    if nonbatch:
        for n in topo:
            a = avals_of(n)[0]
            if a is None:
                continue
            sp = specs.get(id(n), ((None,) * len(a.shape),))[0]
            if any(e is not None for e in sp):
                continue
            nbytes = _aval_bytes(a)
            if nbytes < min_bytes:
                continue
            if n.is_variable and n.name not in step_inputs:
                rep.findings.add(Finding(
                    "shard.memory", "implicit-replication", WARN,
                    f"param '{n.name}' ({nbytes} bytes) is fully "
                    f"replicated while the mesh has a >1-device non-"
                    f"batch axis; every device holds a full copy",
                    node=n.name))
            elif not n.is_variable:
                rep.findings.add(Finding(
                    "shard.memory", "implicit-replication", WARN,
                    f"activation '{n.name}' ({nbytes} bytes) is fully "
                    f"replicated while the mesh has a >1-device non-"
                    f"batch axis", node=n.name))

    # ---- shard-fallback findings (one per op name) ---------------------
    for opname, count in sorted(rep.fallback_ops.items()):
        rep.findings.add(Finding(
            "shard.propagate", "shard-fallback", HINT,
            f"no propagation rule for op '{opname}' (x{count}); outputs "
            f"assumed replicated — per-device costs are upper bounds "
            f"there", node=opname))

    # ---- per-device peak HBM (sharded liveness) ------------------------
    rep.per_device_peak_hbm_bytes = _sharded_liveness(
        symbol, topo, env, specs, axes)
    rep.replicated_peak_hbm_bytes = _sharded_liveness(
        symbol, topo, env, None, axes)

    # ---- summary -------------------------------------------------------
    mesh_str = ",".join(f"{k}={v}" for k, v in axes.items())
    peak = rep.per_device_peak_hbm_bytes
    rep.findings.add(Finding(
        "shard.summary", "shard-summary", HINT,
        f"mesh {mesh_str or '(none)'}: per-device peak HBM "
        f"{(peak or 0) / _MB:.2f} MB "
        f"(replicated {(rep.replicated_peak_hbm_bytes or 0) / _MB:.2f} "
        f"MB), {len(rep.collectives)} tp/GSPMD collectives "
        f"({rep.ici_bytes_per_step} ICI bytes/step), "
        f"{len(rep.reshards)} reshard edges, "
        f"{sum(rep.fallback_ops.values())} fallback ops"))
    return rep


def _sharded_liveness(symbol, topo, env, specs, axes):
    """`cost._liveness_pass`'s walk with PER-DEVICE buffer sizes: every
    entry's bytes divide by its shard count (specs=None -> replicated
    sizes, i.e. the single-device peak)."""
    if any(env.get(id(n)) is None for n in topo):
        return None

    def nbytes(node, idx, aval):
        if aval is None:
            return 0
        if specs is None:
            return _aval_bytes(aval)
        sp = specs.get(id(node))
        spec = sp[idx] if sp is not None and idx < len(sp) else \
            (None,) * len(aval.shape)
        return _sharded_bytes(aval, spec, axes)

    pos = {id(n): i for i, n in enumerate(topo)}
    end = len(topo)
    last_use = {}
    for node in topo:
        for src, idx in node.inputs:
            key = (id(src), idx)
            last_use[key] = max(last_use.get(key, -1), pos[id(node)])
    for node, idx in symbol._entries:
        last_use[(id(node), idx)] = end

    entry_bytes = {}
    for node in topo:
        for i, a in enumerate(env[id(node)]):
            entry_bytes[(id(node), i)] = nbytes(node, i, a)

    var_ids = {id(n) for n in topo if n.is_variable}
    alive = sum(entry_bytes[(id(n), 0)] for n in topo if n.is_variable)
    peak = alive
    for i, node in enumerate(topo):
        if node.is_variable:
            continue
        alive += sum(entry_bytes[(id(node), k)]
                     for k in range(len(env[id(node)])))
        peak = max(peak, alive)
        for key, last in list(last_use.items()):
            if last == i:
                if key[0] not in var_ids:
                    alive -= entry_bytes.get(key, 0)
                del last_use[key]
    return int(peak)


# ---------------------------------------------------------------------------
# collectives: dp bucket plan + tp/GSPMD psums, one combined economy
# ---------------------------------------------------------------------------

def shard_collectives(symbol, shapes=None, mesh="dp=8", rules=None,
                      dtypes=None, cap_bytes=None, batch_axis="dp",
                      name=None, report=None):
    """The full per-step ICI economy of a sharded training step.

    The dp gradient exchange reuses `cost.enumerate_collectives` — the
    SAME `kvstore.plan_buckets` rule in the same reversed-parameter
    priority order, so the dp half is byte-exact against measured
    `KVStore.stats()` / `FusedTrainStep.pod_stats`.  Gradients of
    tp-sharded params exchange at their per-device shard size.  The
    tp/GSPMD half comes from the propagation pass (psums + reshard
    gathers).  Returns a dict; the ShardReport rides under "report"
    when the caller did not pass one in.
    """
    from .cost import enumerate_collectives
    rep = report if report is not None else analyze_sharding(
        symbol, shapes=shapes, mesh=mesh, rules=rules, dtypes=dtypes,
        batch_axis=batch_axis, name=name)
    axes = rep.mesh
    dp = _axis_size(batch_axis, axes)

    from . import graph_passes as gp
    topo = symbol._topo()
    env = gp._abstract_env(symbol, shapes, dtypes)
    step_inputs = {n.name for n in topo if n.is_variable and
                   (n.name.startswith("data") or n.name.endswith("_label")
                    or "state" in n.name)}
    grad_shapes, grad_dtypes = [], []
    for n in topo:
        if not n.is_variable or n.name in step_inputs:
            continue
        avals = env.get(id(n))
        a = avals[0] if avals else None
        if a is None:
            continue
        sp = rep.specs.get(n.name, (None,) * len(a.shape))
        shape = tuple(int(d) // _axis_size(ax, axes)
                      for d, ax in zip(a.shape, sp))
        grad_shapes.append(shape)
        grad_dtypes.append(np.dtype(a.dtype))

    dp_stats = None
    if dp > 1 and grad_shapes:
        dp_stats = enumerate_collectives(
            grad_shapes, dtypes=grad_dtypes, dp=dp, cap_bytes=cap_bytes,
            name=f"{rep.target}-dp")
    tp_ici = rep.ici_bytes_per_step
    total = tp_ici + (dp_stats["ici_bytes_per_chip"] if dp_stats else 0)
    return {
        "mesh": dict(axes),
        "dp": dp_stats,
        "tp": {"collectives_per_step": len(rep.collectives),
               "ici_bytes_per_step": tp_ici,
               "reshard_edges": len(rep.reshards)},
        "ici_bytes_per_step": int(total),
        "report": rep,
    }


# ---------------------------------------------------------------------------
# the bench set: what --shard-report and the budgets gate analyze
# ---------------------------------------------------------------------------

def lm_bench_symbol():
    """The committed LM bench program (small but tp-divisible)."""
    from ..llm.model import lm_symbol, LMConfig
    cfg = LMConfig(vocab_size=128, num_layers=2, num_heads=2, hidden=32,
                   max_len=32, eos_id=0)
    return lm_symbol(cfg), {"data": (8, 16), "softmax_label": (8, 16)}, \
        {"data": "int32", "softmax_label": "int32"}


def analyze_shard_bench_set(mesh="dp=2,tp=2", cap_bytes=None,
                            batch_axis="dp"):
    """Run mxshard over the committed bench programs: the three mxcost
    convnets under the mesh's dp axis (no rule set — a convnet under
    megatron rules is not a coverage gap, and dp params replicate by
    design) and the LM bench symbol under the full mesh with megatron
    rules.  Returns {name: result dict} ready for the budgets gate."""
    from .cost import bench_programs
    from ..parallel.tensor_parallel import ShardingRules
    axes = _mesh_axes(mesh)
    dp = _axis_size(batch_axis, axes)
    out = {}
    for pname, (sym, shapes, dtypes) in sorted(bench_programs().items()):
        stats = shard_collectives(
            sym, shapes=shapes, mesh={batch_axis: dp}, rules=None,
            dtypes=dtypes, cap_bytes=cap_bytes, batch_axis=batch_axis,
            name=pname)
        rep = stats.pop("report")
        entry = rep.as_dict()
        entry["collectives"] = stats
        entry["ici_bytes_per_step"] = stats["ici_bytes_per_step"]
        out[pname] = entry
    sym, shapes, dtypes = lm_bench_symbol()
    stats = shard_collectives(
        sym, shapes=shapes, mesh=axes,
        rules=ShardingRules.megatron(tp_axis="tp") if
        _axis_size("tp", axes) > 1 else None,
        dtypes=dtypes, cap_bytes=cap_bytes, batch_axis=batch_axis,
        name="llm.lm_micro")
    rep = stats.pop("report")
    entry = rep.as_dict()
    entry["collectives"] = stats
    entry["ici_bytes_per_step"] = stats["ici_bytes_per_step"]
    out["llm.lm_micro"] = entry
    return out


# ---------------------------------------------------------------------------
# budget gate (COST_BUDGETS.json "sharding" section)
# ---------------------------------------------------------------------------

_BUDGET_METRICS = ("per_device_peak_hbm_bytes", "ici_bytes_per_step")
# both metrics are fully static and deterministic: any growth is a real
# program change, so the tolerance is tight
_BUDGET_TOL = {"per_device_peak_hbm_bytes": 0.01,
               "ici_bytes_per_step": 0.01}


def snapshot_shard_budgets(results, mesh="dp=2,tp=2"):
    """The committed-baseline shape for COST_BUDGETS.json["sharding"]."""
    progs = {}
    for name, entry in sorted(results.items()):
        progs[name] = {m: int(entry.get(m) or 0) for m in _BUDGET_METRICS}
    return {"mesh": mesh if isinstance(mesh, str)
            else ",".join(f"{k}={v}" for k, v in _mesh_axes(mesh).items()),
            "programs": progs}


def check_shard_budgets(results, budgets):
    """Gate bench-set results against the committed baseline with the
    same `_compare` currency the mxcost budget gate uses."""
    from . import budgets as _budgets
    report = Report(target="shard-budgets")
    deltas = {}
    section = (budgets or {}).get("sharding", {})
    baseline = section.get("programs", {})
    for name, entry in sorted(results.items()):
        base = baseline.get(name)
        if base is None:
            report.add(Finding(
                "cost.budget", "budget-missing", HINT,
                f"no sharding baseline for program '{name}'; snapshot "
                f"with --write-budgets", node=name))
            continue
        for metric in _BUDGET_METRICS:
            if metric not in base:
                continue
            _budgets._compare(report, deltas, f"sharding.{name}", metric,
                              int(entry.get(metric) or 0), base[metric],
                              _BUDGET_TOL[metric], slack=False)
    return report, deltas


# ---------------------------------------------------------------------------
# measured cross-check: static dp plan vs a real KVStore push
# ---------------------------------------------------------------------------

def measured_ici_check(mesh="dp=4", cap_bytes=None, batch_axis="dp"):
    """Push the bench convnet's (per-device-sharded) gradients through a
    real device KVStore and compare the measured counters against the
    static dp plan.  Because `enumerate_collectives` applies the SAME
    `kvstore.plan_buckets` rule, the agreement is byte-exact — the
    returned ``agreement_pct`` is the CI gate (must be <= 10)."""
    import jax
    from .. import kvstore as _kvstore
    from .. import nd as _nd
    from ..context import tpu as _tpu
    from .cost import build_bench_convnet, BENCH_SHAPE

    axes = _mesh_axes(mesh)
    dp = _axis_size(batch_axis, axes)
    ndev = len(jax.devices())
    dp = max(1, min(dp, ndev))

    sym, shapes = build_bench_convnet("float32")
    kv = _kvstore.create("tpu")
    if cap_bytes is None:
        cap_bytes = kv._bucket_cap_bytes

    # the mesh the check runs under: the requested axes, with dp
    # clamped to the devices this host actually has
    axes = dict(axes)
    axes[batch_axis] = dp
    static = shard_collectives(sym, shapes=shapes, mesh=axes, rules=None,
                               dtypes=None, cap_bytes=cap_bytes,
                               batch_axis=batch_axis, name="convnet")
    rep = static["report"]

    # per-device gradient shapes (tp-sharded params exchange shards)
    arg_shapes, _, _ = sym.infer_shape(data=BENCH_SHAPE)
    grad_shapes = []
    for pname, shape in zip(sym.list_arguments(), arg_shapes):
        if pname == "data":
            continue
        sp = rep.specs.get(pname, (None,) * len(shape))
        grad_shapes.append(tuple(int(d) // _axis_size(ax, axes)
                                 for d, ax in zip(shape, sp)))
    devs = [_tpu(i) for i in range(dp)]
    keys = [str(i) for i in range(len(grad_shapes))]
    for k, s in zip(keys, grad_shapes):
        kv.init(k, _nd.zeros(s))
    vals = [[_nd.ones(s, ctx=d) for d in devs] for s in grad_shapes]
    kv.push(keys, vals)
    meas = kv.stats()
    dp_stats = static["dp"] or {}
    measured_bytes = int(meas["bytes_reduced"])
    static_bytes = int(dp_stats.get("bytes_per_step") or 0)
    agreement = abs(static_bytes - measured_bytes) * 100.0 / \
        max(1, measured_bytes)
    return {
        "mesh": dict(axes),
        "dp": dp,
        "static_bytes_per_step": static_bytes,
        "measured_bytes_per_step": measured_bytes,
        "static_collectives_per_step":
            int(dp_stats.get("collectives_per_step") or 0),
        "measured_allreduce_dispatches":
            int(meas["allreduce_dispatches"]),
        "agreement_pct": round(agreement, 3),
        "ok": agreement <= 10.0 and
            int(dp_stats.get("collectives_per_step") or 0) ==
            int(meas["allreduce_dispatches"]),
    }
