"""mxcost — static graph cost & communication analysis.

The runtime only reveals cost problems after the fact: BENCH_OPS showed
the int8 convnet 1.8x *slower* than fp32, BENCH_r05 pinned h2d at
13.8 MB/s, and the pod fast path's whole value is its O(buckets)
collective economy — yet none of those numbers could be predicted (or
guarded) before a run.  mxcost is the predictive half: it walks Symbol
graphs and traced jaxprs and derives, per program,

* **per-op FLOPs / bytes-moved / arithmetic intensity** with a roofline
  classification against a device profile (TVM's per-op cost-model
  stance, PAPERS.md) — `analyze_symbol`, `analyze_callable`;
* a **dtype-flow pass** tracking precision through the graph: the
  ``dequantize → fp32 dot`` chains that are the static signature of the
  int8-slower-than-fp32 defect, quantized ops whose registered compute
  dtype is fp32, and f32 upcasts feeding fp32 dots inside bf16 graphs;
* a **collective enumerator** — `enumerate_collectives` applies the
  SAME `kvstore.plan_buckets` rule (and priority order) the runtime
  scheduler and the pod fast path use, so collectives-per-step and
  bytes-on-the-ICI are derived statically and cross-check against
  `KVStore.stats()` measured counters (the MLPerf-pods paper treats
  per-step communication bytes as a first-class budget);
* a **liveness / peak-HBM pass** with donation-opportunity findings
  (step-boundary buffers that die mid-program but are not donated);
* **hidden host-transfer detection** — callback primitives inside a
  traced program (the jaxpr side; `source_lint`'s
  ``host-transfer-in-graph`` is the AST side of the same hazard).

Results are ordinary `Finding`s/`Report`s, so they compose with every
other pass: ``tools/mxlint.py --cost-report`` renders them, and
`analysis/budgets.py` turns a committed ``COST_BUDGETS.json`` baseline
into hard CI failures on regression (new collectives, +bytes/step,
+peak HBM, new dequant chains).
"""
from __future__ import annotations

import math

import numpy as _np

from .findings import Finding, Report, ERROR, WARN, HINT

__all__ = ["DeviceProfile", "PROFILES", "get_profile", "OpCost",
           "ProgramCost", "analyze_symbol", "analyze_callable",
           "analyze_jaxpr", "jaxpr_dying_inputs", "enumerate_collectives",
           "analyze_executor", "build_bench_convnet", "bench_programs",
           "analyze_bench_set", "CODES"]

# every code the cost passes emit (the findings.CODE_TABLE cross-check)
CODES = ("cost-summary", "dequant-fp32-dot", "quantized-fp32-compute",
         "f32-upcast-in-bf16", "hidden-host-transfer",
         "donation-opportunity", "collective-summary",
         "collective-o-params")


# ---------------------------------------------------------------------------
# device profiles
# ---------------------------------------------------------------------------

class DeviceProfile:
    """Peak numbers the roofline classifies against.  Values are the
    published per-chip peaks (approximate by design: the classification
    needs the right order of magnitude, not the datasheet's third
    digit).  Note v3 has NO int8 MXU speedup — int8 peak == bf16 peak —
    which is exactly why a dequant/requant round trip makes int8
    slower, never faster, there."""

    __slots__ = ("name", "peak_flops", "hbm_bps", "ici_bps", "hbm_bytes")

    def __init__(self, name, peak_flops, hbm_bps, ici_bps, hbm_bytes):
        self.name = name
        self.peak_flops = dict(peak_flops)   # dtype name -> flops/s
        self.hbm_bps = float(hbm_bps)        # bytes/s
        self.ici_bps = float(ici_bps)        # bytes/s per link
        self.hbm_bytes = int(hbm_bytes)

    def peak(self, dtype):
        key = _dtype_key(dtype)
        if key in self.peak_flops:
            return self.peak_flops[key]
        if key.startswith("int") or key.startswith("uint"):
            return self.peak_flops.get("int8",
                                       self.peak_flops["float32"])
        if key == "float64":
            return self.peak_flops["float32"] / 10.0  # emulated
        return self.peak_flops.get("float32")

    def ridge(self, dtype):
        """Arithmetic intensity (flops/byte) above which `dtype` math is
        compute-bound on this device."""
        return self.peak(dtype) / self.hbm_bps

    def as_dict(self):
        return {"name": self.name, "peak_flops": dict(self.peak_flops),
                "hbm_gbps": self.hbm_bps / 1e9,
                "ici_gbps": self.ici_bps / 1e9,
                "hbm_gib": self.hbm_bytes / (1 << 30)}


PROFILES = {
    "tpu-v3": DeviceProfile(
        "tpu-v3",
        {"bfloat16": 123e12, "float32": 16e12, "int8": 123e12},
        hbm_bps=900e9, ici_bps=100e9, hbm_bytes=32 << 30),
    "tpu-v4": DeviceProfile(
        "tpu-v4",
        {"bfloat16": 275e12, "float32": 34e12, "int8": 275e12},
        hbm_bps=1200e9, ici_bps=100e9, hbm_bytes=32 << 30),
    # the CI host: classification sanity only, not a perf claim
    "cpu-host": DeviceProfile(
        "cpu-host", {"bfloat16": 100e9, "float32": 200e9, "int8": 400e9},
        hbm_bps=20e9, ici_bps=5e9, hbm_bytes=8 << 30),
}


def get_profile(name=None):
    if isinstance(name, DeviceProfile):
        return name
    if name is None:
        from .. import config as _config
        name = _config.get("MXNET_COST_PROFILE")
    try:
        return PROFILES[name]
    except KeyError:
        raise ValueError(f"unknown device profile {name!r} "
                         f"(have {sorted(PROFILES)})") from None


def _dtype_key(dtype):
    try:
        return _np.dtype(dtype).name
    except TypeError:
        return str(dtype)   # bfloat16 (ml_dtypes) has a numpy dtype; a
                            # bare string falls through unchanged


def _aval_bytes(aval):
    size = int(_np.prod(aval.shape)) if getattr(aval, "shape", ()) else 1
    try:
        item = _np.dtype(aval.dtype).itemsize
    except TypeError:
        item = 4
    return size * item


def _aval_elems(aval):
    return int(_np.prod(aval.shape)) if getattr(aval, "shape", ()) else 1


# ---------------------------------------------------------------------------
# per-op cost records
# ---------------------------------------------------------------------------

class OpCost:
    """One node's static cost: flops, bytes moved, intensity, bound."""

    __slots__ = ("node", "op", "flops", "bytes_in", "bytes_out",
                 "compute_dtype", "ai", "bound")

    def __init__(self, node, op, flops, bytes_in, bytes_out,
                 compute_dtype, ai, bound):
        self.node = node
        self.op = op
        self.flops = flops
        self.bytes_in = bytes_in
        self.bytes_out = bytes_out
        self.compute_dtype = compute_dtype
        self.ai = ai
        self.bound = bound   # "compute" | "memory" | "trivial" | "host"

    @property
    def bytes_moved(self):
        return self.bytes_in + self.bytes_out

    def as_dict(self):
        return {"node": self.node, "op": self.op, "flops": self.flops,
                "bytes_moved": self.bytes_moved,
                "compute_dtype": self.compute_dtype,
                "arithmetic_intensity": round(self.ai, 3),
                "bound": self.bound}


class ProgramCost:
    """The analyzer's result for one program: totals, the roofline
    classification, the counters the budget gate compares, and the
    findings (a plain `Report`, so it rides mxlint/runtime_report)."""

    def __init__(self, name, profile):
        self.name = name
        self.profile = profile
        self.per_op = []          # [OpCost]
        self.unknown_ops = 0      # nodes whose avals could not be solved
        self.param_bytes = 0
        self.peak_hbm_bytes = None
        self.collectives = None   # enumerate_collectives() dict
        self.counters = {"dequant_fp32_dot": 0, "quantized_fp32_compute": 0,
                         "f32_upcasts": 0, "host_transfers": 0}
        self.report = Report(target=name)

    # -- totals ---------------------------------------------------------------
    @property
    def flops(self):
        return sum(c.flops for c in self.per_op)

    @property
    def bytes_moved(self):
        return sum(c.bytes_moved for c in self.per_op)

    @property
    def arithmetic_intensity(self):
        b = self.bytes_moved
        return self.flops / b if b else 0.0

    def dominant_dtype(self):
        """Compute dtype carrying the most flops (the roofline row the
        program as a whole is judged against)."""
        by = {}
        for c in self.per_op:
            by[c.compute_dtype] = by.get(c.compute_dtype, 0) + c.flops
        return max(by, key=by.get) if by else "float32"

    def step_time_lb_s(self):
        """Roofline lower bound: the program can never run faster than
        max(flops at peak, bytes at HBM bandwidth)."""
        dt = self.dominant_dtype()
        t_flops = self.flops / self.profile.peak(dt)
        t_mem = self.bytes_moved / self.profile.hbm_bps
        return max(t_flops, t_mem)

    @property
    def bound(self):
        dt = self.dominant_dtype()
        t_flops = self.flops / self.profile.peak(dt)
        t_mem = self.bytes_moved / self.profile.hbm_bps
        if self.counters["host_transfers"]:
            return "host"
        return "compute" if t_flops >= t_mem else "memory"

    def bound_fracs(self):
        total = sum(c.flops for c in self.per_op) or 1
        out = {}
        for c in self.per_op:
            out[c.bound] = out.get(c.bound, 0) + c.flops
        return {k: round(v / total, 4) for k, v in out.items()}

    def as_dict(self, top=8):
        d = {
            "name": self.name,
            "profile": self.profile.name,
            "ops": len(self.per_op),
            "unknown_ops": self.unknown_ops,
            "flops": int(self.flops),
            "bytes_moved": int(self.bytes_moved),
            "param_bytes": int(self.param_bytes),
            "peak_hbm_bytes": (None if self.peak_hbm_bytes is None
                               else int(self.peak_hbm_bytes)),
            "arithmetic_intensity": round(self.arithmetic_intensity, 3),
            "dominant_dtype": self.dominant_dtype(),
            "bound": self.bound,
            "step_time_lb_ms": round(self.step_time_lb_s() * 1e3, 6),
            "bound_fracs": self.bound_fracs(),
            "counters": dict(self.counters),
            "top_ops": [c.as_dict() for c in sorted(
                self.per_op, key=lambda c: -c.flops)[:top]],
            "findings": [f.as_dict() for f in self.report],
        }
        if self.collectives is not None:
            d["collectives"] = dict(self.collectives)
        return d


# ---------------------------------------------------------------------------
# FLOPs rules (symbol ops).  An op can override via OpDef.cost_meta
# {"flops": fn(params, in_avals, out_avals) -> float,
#  "compute_dtype": "float32" | fn(...) -> str} — ops/quantization.py
# registers exactly that metadata (its int8 ops compute in fp32 on this
# design, which IS the defect mxcost exists to flag).
# ---------------------------------------------------------------------------

# ops that lower to MXU matmul/conv work — the roofline's compute rows,
# and the targets the dtype-flow chains are walked toward
DOT_CLASS = frozenset({
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "linalg_gemm", "linalg_gemm2", "RNN",
    "_contrib_quantized_fully_connected", "_contrib_quantized_conv",
})

# ops the dequant/upcast chain walk treats as pass-through (everything
# that is not dot-class is traversed; this set exists only for docs)
_QUANT_OPS = frozenset({"_contrib_quantize", "_contrib_quantize_v2",
                        "quantize", "_contrib_requantize"})
_DEQUANT_OPS = frozenset({"_contrib_dequantize", "dequantize"})
_CAST_OPS = frozenset({"Cast", "cast", "amp_cast"})


def _sym_flops(node, in_avals, out_avals):
    """FLOPs of one symbol node from its solved input/output avals."""
    meta = getattr(node.op, "cost_meta", None) or {}
    rule = meta.get("flops")
    if rule is not None:
        try:
            return float(rule(node.attrs, in_avals, out_avals))
        except Exception:
            pass
    op = node.op.name
    out_elems = sum(_aval_elems(a) for a in out_avals if a is not None)
    if op in ("FullyConnected", "_contrib_quantized_fully_connected"):
        w = in_avals[1]
        return 2.0 * _aval_elems(out_avals[0]) * int(w.shape[-1])
    if op in ("Convolution", "Deconvolution", "_contrib_quantized_conv"):
        w = in_avals[1]
        # per output element: 2 * (in_features/group) * kernel volume
        return 2.0 * _aval_elems(out_avals[0]) * \
            (_aval_elems(w) / int(w.shape[0]))
    if op in ("dot", "batch_dot", "linalg_gemm", "linalg_gemm2"):
        k = int(in_avals[0].shape[-1]) if in_avals[0].shape else 1
        return 2.0 * _aval_elems(out_avals[0]) * k
    if op == "RNN":
        # 4 gate matmuls per step per direction, dominated by h*h
        try:
            h = int(node.attrs.get("state_size"))
            return 8.0 * out_elems * h
        except (TypeError, ValueError):
            return 8.0 * out_elems
    if op in ("Pooling", "_contrib_quantized_pooling"):
        kern = node.attrs.get("kernel") or ()
        kvol = int(_np.prod(kern)) if kern else 1
        if node.attrs.get("global_pool") and in_avals:
            kvol = max(1, _aval_elems(in_avals[0]) //
                       max(1, _aval_elems(out_avals[0])))
        return float(out_elems * kvol)
    if op in ("BatchNorm", "LayerNorm", "InstanceNorm", "L2Normalization"):
        return 8.0 * out_elems
    if op in ("softmax", "Softmax", "SoftmaxOutput", "SoftmaxActivation",
              "log_softmax"):
        return 4.0 * out_elems
    if op in _QUANT_OPS or op in _DEQUANT_OPS:
        return 3.0 * out_elems   # scale + clip/round per element
    return float(out_elems)      # elementwise default: 1 flop/element


def _compute_dtype(node, in_avals, out_avals):
    """The dtype the node's arithmetic actually runs in.  Op metadata
    wins (quantized ops DECLARE fp32 compute); otherwise the widest
    floating dtype among the solved avals, else the output dtype."""
    meta = getattr(node.op, "cost_meta", None) or {}
    declared = meta.get("compute_dtype")
    if callable(declared):
        try:
            declared = declared(node.attrs, in_avals, out_avals)
        except Exception:
            declared = None
    if declared:
        return str(declared)
    widest, width = None, -1
    for a in list(in_avals) + list(out_avals):
        if a is None:
            continue
        key = _dtype_key(a.dtype)
        if key.startswith(("float", "bfloat")):
            w = _np.dtype(a.dtype).itemsize if key != "bfloat16" else 2
            if w > width:
                widest, width = key, w
    if widest is not None:
        return widest
    return _dtype_key(out_avals[0].dtype) if out_avals and \
        out_avals[0] is not None else "float32"


_TRIVIAL_BYTES = 4 << 10   # below this, dispatch overhead dominates


def _classify(op_name, flops, bytes_moved, compute_dtype, profile):
    if bytes_moved <= _TRIVIAL_BYTES:
        return "trivial"
    ai = flops / max(1, bytes_moved)
    return "compute" if ai >= profile.ridge(compute_dtype) else "memory"


# ---------------------------------------------------------------------------
# symbol analysis
# ---------------------------------------------------------------------------

def analyze_symbol(symbol, shapes=None, dtypes=None, profile=None,
                   target=None, step_inputs=None):
    """Static cost analysis of a Symbol graph.

    Parameters
    ----------
    symbol : Symbol
    shapes : {var_name: shape} seeding abstract evaluation (same
        convention as `infer_shape` kwargs / `analysis.check`).
    dtypes : {var_name: dtype} — seeds variable dtypes that are not
        declared on the graph (a quantized model's int8 weights live in
        its params dict, not its variable attrs).
    profile : DeviceProfile or name (default: MXNET_COST_PROFILE).
    step_inputs : iterable of variable names refilled every step (data/
        label batches).  Default: ``data*`` and ``*_label`` variables.
        These are the donation-opportunity candidates — their buffers
        die inside the step by definition.
    """
    from .graph_passes import _abstract_env

    profile = get_profile(profile)
    topo = symbol._topo()
    name = target or "symbol"
    prog = ProgramCost(name, profile)
    try:
        env = _abstract_env(symbol, shapes, dtypes=dtypes)
    except Exception:
        env = {}

    def avals_of(node):
        return env.get(id(node))

    # -- per-op cost ---------------------------------------------------------
    for node in topo:
        if node.is_variable:
            avals = avals_of(node)
            if avals and avals[0] is not None:
                prog.param_bytes += _aval_bytes(avals[0])
            continue
        out_avals = avals_of(node)
        in_avals = []
        for src, idx in node.inputs:
            e = avals_of(src)
            in_avals.append(e[idx] if e and idx < len(e) else None)
        if out_avals is None or any(a is None for a in in_avals):
            prog.unknown_ops += 1
            continue
        flops = _sym_flops(node, in_avals, out_avals)
        b_in = sum(_aval_bytes(a) for a in in_avals)
        b_out = sum(_aval_bytes(a) for a in out_avals if a is not None)
        cdt = _compute_dtype(node, in_avals, out_avals)
        bound = _classify(node.op.name, flops, b_in + b_out, cdt, profile)
        prog.per_op.append(OpCost(node.name, node.op.name, flops, b_in,
                                  b_out, cdt, flops / max(1, b_in + b_out),
                                  bound))

    _dtype_flow_pass(symbol, topo, env, prog)
    _liveness_pass(symbol, topo, env, prog, step_inputs)
    prog.report.add(Finding(
        "cost.roofline", "cost-summary", HINT,
        "%s: %d op(s), %.3g GFLOPs, %.3g MB moved, AI %.1f flops/byte "
        "-> %s-bound on %s (%s); step >= %.3g ms; peak HBM %s"
        % (name, len(prog.per_op), prog.flops / 1e9,
           prog.bytes_moved / (1 << 20), prog.arithmetic_intensity,
           prog.bound, profile.name, prog.dominant_dtype(),
           prog.step_time_lb_s() * 1e3,
           "?" if prog.peak_hbm_bytes is None
           else "%.2f MB" % (prog.peak_hbm_bytes / (1 << 20))),
        location=name))
    return prog


# -- dtype flow --------------------------------------------------------------

def _consumer_map(topo):
    out = {}
    for node in topo:
        for src, idx in node.inputs:
            out.setdefault(id(src), []).append(node)
    return out


def _walk_to_dot(start, consumers):
    """BFS forward from `start` to the nearest dot-class node; returns
    (target_node, [path names start..target]) or (None, None).  The walk
    traverses everything that is NOT dot-class (quantize ops, pooling,
    reshapes, activations — the 'transparent' chain links)."""
    from collections import deque
    prev = {id(start): None}
    by_id = {id(start): start}
    q = deque([start])
    while q:
        node = q.popleft()
        for c in consumers.get(id(node), ()):
            if id(c) in prev:
                continue
            prev[id(c)] = id(node)
            by_id[id(c)] = c
            if not c.is_variable and c.op.name in DOT_CLASS:
                path, cur = [], id(c)
                while cur is not None:
                    path.append(by_id[cur].name)
                    cur = prev[cur]
                return c, list(reversed(path))
            q.append(c)
    return None, None


def _node_compute_dtype(node, env):
    in_avals = []
    for src, idx in node.inputs:
        e = env.get(id(src))
        in_avals.append(e[idx] if e and idx < len(e) else None)
    out_avals = env.get(id(node)) or ()
    return _compute_dtype(node, [a for a in in_avals if a is not None],
                          [a for a in out_avals if a is not None])


def _dtype_flow_pass(symbol, topo, env, prog):
    """Precision-lattice findings: dequantize chains that end in an
    fp32 dot, quantized ops that declare fp32 compute, and f32 upcasts
    feeding fp32 dots inside bf16-dominant graphs."""
    consumers = _consumer_map(topo)

    for node in topo:
        if node.is_variable:
            continue
        op = node.op.name
        # (1) the int8-slower-than-fp32 signature: int8 values round-trip
        # through fp32 on their way into the next dot
        if op in _DEQUANT_OPS:
            tgt, path = _walk_to_dot(node, consumers)
            if tgt is not None and \
                    _node_compute_dtype(tgt, env) == "float32":
                prog.counters["dequant_fp32_dot"] += 1
                prog.report.add(Finding(
                    "cost.dtype", "dequant-fp32-dot", WARN,
                    "dequantized values from '%s' reach '%s' (%s) which "
                    "computes in float32 (chain: %s): the int8 path "
                    "round-trips through fp32 before the next dot — the "
                    "static signature of the int8-slower-than-fp32 "
                    "defect; fuse the scale into the dot epilogue "
                    "instead of dequantizing between quantized ops"
                    % (node.name, tgt.name, tgt.op.name,
                       " -> ".join(path)), node=node.name))
        # (2) the defect's other half: an "int8" op whose registered
        # compute dtype is fp32 never sees int8 MXU throughput
        meta = getattr(node.op, "cost_meta", None) or {}
        if meta.get("quantized") and \
                _node_compute_dtype(node, env) == "float32" and \
                op in DOT_CLASS:
            prog.counters["quantized_fp32_compute"] += 1
            prog.report.add(Finding(
                "cost.dtype", "quantized-fp32-compute", WARN,
                "quantized op '%s' (%s) registers float32 compute: the "
                "int8 inputs are upcast and the matmul/conv runs at the "
                "fp32 MXU rate — int8 buys bandwidth here, never "
                "compute; lower to a native int8 dot with a fused "
                "scale/dequant epilogue" % (node.name, op),
                node=node.name))
        # (3) an explicit bf16 -> f32 upcast feeding an fp32 dot: the
        # producer already computed the value in bf16, so the MXU could
        # have run the downstream dot at the bf16 rate — the upcast
        # forces ~8x fp32 throughput (a clean bf16 graph has no such
        # cast, and a cast feeding only a head/loss never reaches a dot)
        if op in _CAST_OPS:
            in_aval = None
            src, idx = node.inputs[0]
            e = env.get(id(src))
            if e and idx < len(e):
                in_aval = e[idx]
            out_avals = env.get(id(node))
            if in_aval is not None and out_avals and \
                    out_avals[0] is not None and \
                    _dtype_key(in_aval.dtype) == "bfloat16" and \
                    _dtype_key(out_avals[0].dtype) == "float32":
                tgt, path = _walk_to_dot(node, consumers)
                if tgt is not None and \
                        _node_compute_dtype(tgt, env) == "float32":
                    prog.counters["f32_upcasts"] += 1
                    prog.report.add(Finding(
                        "cost.dtype", "f32-upcast-in-bf16", WARN,
                        "'%s' upcasts bfloat16 to float32 and the value "
                        "reaches '%s' (%s) as an fp32 dot (chain: %s): "
                        "that dot pays the fp32 MXU rate (~8x slower "
                        "than bf16) for a value the graph already "
                        "computed in bf16 — keep the chain bf16 or "
                        "cast after the dot"
                        % (node.name, tgt.name, tgt.op.name,
                           " -> ".join(path)), node=node.name))


# -- liveness / peak HBM -----------------------------------------------------

def _liveness_pass(symbol, topo, env, prog, step_inputs):
    """Allocate outputs in topo order, free TRANSIENTS after their last
    consumer, track the high-water mark.  Conservative on both sides:
    a node's outputs allocate before its inputs free (XLA cannot alias
    in general), and variable buffers (params, step inputs) are never
    freed — the caller holds them, so without donation they stay
    resident for the whole program even after their last graph use."""
    from .. import config as _config
    if any(env.get(id(n)) is None for n in topo):
        return   # partial inference: a peak claim would be fiction
    pos = {id(n): i for i, n in enumerate(topo)}
    end = len(topo)
    last_use = {}
    for node in topo:
        for src, idx in node.inputs:
            key = (id(src), idx)
            last_use[key] = max(last_use.get(key, -1), pos[id(node)])
    for node, idx in symbol._entries:       # heads live to the end
        last_use[(id(node), idx)] = end
    last_use_full = dict(last_use)

    entry_bytes = {}
    for node in topo:
        avals = env.get(id(node))
        for i, a in enumerate(avals):
            entry_bytes[(id(node), i)] = _aval_bytes(a)

    # every variable (params + step inputs) is resident at dispatch —
    # and stays resident: undonated caller-held buffers never free
    var_ids = {id(n) for n in topo if n.is_variable}
    alive = sum(entry_bytes[(id(n), 0)] for n in topo if n.is_variable)
    peak = alive
    for i, node in enumerate(topo):
        if node.is_variable:
            continue
        alive += sum(entry_bytes[(id(node), k)]
                     for k in range(len(env[id(node)])))
        peak = max(peak, alive)
        for key, last in list(last_use.items()):
            if last == i:
                if key[0] not in var_ids:   # transients only
                    alive -= entry_bytes.get(key, 0)
                del last_use[key]
    prog.peak_hbm_bytes = peak

    # donation opportunities: step-boundary inputs whose buffer dies
    # mid-program but is re-staged from host every step anyway
    if step_inputs is None:
        step_inputs = {n.name for n in topo if n.is_variable and
                       (n.name.startswith("data") or
                        n.name.endswith("_label") or
                        "state" in n.name)}
    else:
        step_inputs = set(step_inputs)
    min_bytes = int(float(_config.get("MXNET_COST_DONATE_MIN_MB"))
                    * (1 << 20))
    for node in topo:
        if not node.is_variable or node.name not in step_inputs:
            continue
        nbytes = entry_bytes.get((id(node), 0), 0)
        died = last_use_full.get((id(node), 0), end) < end
        if nbytes >= min_bytes and died:
            prog.report.add(Finding(
                "cost.memory", "donation-opportunity", HINT,
                "step input '%s' (%.2f MB) dies inside the step but is "
                "re-staged from host every dispatch — donating its "
                "buffer lets XLA reuse the space in-place "
                "(donate_argnums / the fused step's donated carry)"
                % (node.name, nbytes / (1 << 20)), node=node.name))


# ---------------------------------------------------------------------------
# jaxpr analysis (traced fused steps / plain jax callables)
# ---------------------------------------------------------------------------

_COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "all_to_all", "ppermute", "pmax", "pmin",
    "reduce_scatter", "psum_scatter", "allreduce", "all_reduce"})
_HOST_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_local_array_to_global_array", "outside_call"})
_DOT_PRIMS = frozenset({"dot_general", "conv_general_dilated"})


def _subjaxprs(params):
    for v in params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for x in items:
            if hasattr(x, "eqns"):
                yield x
            elif hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr


def analyze_jaxpr(closed, name="jaxpr", profile=None, donated=()):
    """Walk a (Closed)Jaxpr's equations: per-primitive flops/bytes with
    the same roofline classification as the symbol side, collective
    binds counted with their payload bytes, and callback primitives
    flagged as hidden host transfers.  `scan` bodies multiply by trip
    count; `cond` branches all count (a deliberate upper bound)."""
    profile = get_profile(profile)
    prog = ProgramCost(name, profile)
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    coll = {"count": 0, "bytes": 0}

    def var_bytes(atoms):
        return sum(_aval_bytes(a.aval) for a in atoms
                   if hasattr(a, "aval"))

    def walk(jx, mult):
        for eqn in jx.eqns:
            p = eqn.primitive.name
            if p == "scan":
                length = int(eqn.params.get("length", 1))
                for sub in _subjaxprs(eqn.params):
                    walk(sub, mult * length)
                continue
            if p in ("while", "cond", "pjit", "closed_call", "core_call",
                     "custom_jvp_call", "custom_vjp_call",
                     "custom_vjp_call_jaxpr", "remat", "remat2",
                     "checkpoint", "shard_map", "named_call", "xla_call"):
                for sub in _subjaxprs(eqn.params):
                    walk(sub, mult)
                continue
            b_in = var_bytes(eqn.invars)
            b_out = var_bytes(eqn.outvars)
            out_elems = sum(_aval_elems(a.aval) for a in eqn.outvars
                            if hasattr(a, "aval"))
            if p in _HOST_PRIMS:
                prog.counters["host_transfers"] += mult
                prog.report.add(Finding(
                    "cost.host", "hidden-host-transfer", WARN,
                    "primitive '%s' inside traced program '%s' crosses "
                    "to the host (%.1f KB per call%s): the device "
                    "pipeline stalls on the round trip every step — "
                    "move the computation in-graph or hoist it out of "
                    "the traced region"
                    % (p, name, (b_in + b_out) / 1024.0,
                       ", x%d via scan" % mult if mult > 1 else ""),
                    location=name))
                prog.per_op.append(OpCost(p, p, 0.0, b_in * mult,
                                          b_out * mult, "float32", 0.0,
                                          "host"))
                continue
            if p in _COLLECTIVE_PRIMS:
                coll["count"] += mult
                coll["bytes"] += mult * b_in
                continue
            if p == "dot_general":
                (lc, _rc), _batch = eqn.params["dimension_numbers"]
                lhs = eqn.invars[0].aval
                k = int(_np.prod([lhs.shape[d] for d in lc])) or 1
                flops = 2.0 * out_elems * k
            elif p == "conv_general_dilated":
                rhs = eqn.invars[1].aval
                dn = eqn.params["dimension_numbers"]
                o_feat = rhs.shape[dn.rhs_spec[0]]
                flops = 2.0 * out_elems * (_aval_elems(rhs) /
                                           max(1, o_feat))
            elif p.startswith("reduce_") or p in ("argmax", "argmin"):
                flops = float(sum(_aval_elems(a.aval)
                                  for a in eqn.invars
                                  if hasattr(a, "aval")))
            else:
                flops = float(out_elems)
            flops *= mult
            cdt = "float32"
            for a in list(eqn.invars) + list(eqn.outvars):
                if hasattr(a, "aval"):
                    key = _dtype_key(a.aval.dtype)
                    if key.startswith(("float", "bfloat")):
                        cdt = key
                        break
            bound = _classify(p, flops, (b_in + b_out) * mult, cdt,
                              profile)
            prog.per_op.append(OpCost(p, p, flops, b_in * mult,
                                      b_out * mult, cdt,
                                      flops / max(1, (b_in + b_out) * mult),
                                      bound))

    walk(jaxpr, 1)
    if coll["count"]:
        prog.collectives = {"collectives_per_step": coll["count"],
                            "bytes_per_step": coll["bytes"]}
    # donation opportunities: an input aval that matches an output aval
    # and is not donated could carry the result in place
    donated = set(donated)
    out_avals = [v.aval for v in jaxpr.outvars if hasattr(v, "aval")]
    for i, v in enumerate(jaxpr.invars):
        if i in donated or not hasattr(v, "aval"):
            continue
        a = v.aval
        if _aval_bytes(a) < (1 << 20):
            continue
        if any(o.shape == a.shape and o.dtype == a.dtype
               for o in out_avals):
            prog.report.add(Finding(
                "cost.memory", "donation-opportunity", HINT,
                "input %d (%s%s, %.2f MB) matches an output aval but is "
                "not donated: the step pays a full extra buffer where "
                "donate_argnums would update in place"
                % (i, _dtype_key(a.dtype), list(a.shape),
                   _aval_bytes(a) / (1 << 20)), location=name))
    prog.report.add(Finding(
        "cost.roofline", "cost-summary", HINT,
        "%s: %d eqn(s), %.3g GFLOPs, %.3g MB moved, AI %.1f -> %s-bound"
        % (name, len(prog.per_op), prog.flops / 1e9,
           prog.bytes_moved / (1 << 20), prog.arithmetic_intensity,
           prog.bound), location=name))
    return prog


def jaxpr_dying_inputs(closed, indices=None):
    """Flat input positions whose buffers provably DIE inside the traced
    program: the invar is never aliased straight through to an outvar,
    so donating that argument lets XLA reuse its buffer for
    intermediates (lower peak HBM, no copy).  `indices` restricts the
    check to a candidate slice of the flattened inputs.

    This is the trace-time liveness oracle `fused.FusedTrainStep`
    consults for auto-donation (MXNET_FUSED_AUTODONATE): an input that
    IS returned — an echoed batch, a passthrough label — stays
    undonated, because its buffer must outlive the step."""
    jaxpr = closed.jaxpr
    live_out = {id(v) for v in jaxpr.outvars}
    rng = range(len(jaxpr.invars)) if indices is None else indices
    return [i for i in rng
            if 0 <= i < len(jaxpr.invars)
            and id(jaxpr.invars[i]) not in live_out]


def analyze_callable(fn, avals, name=None, profile=None,
                     donate_argnums=()):
    """Trace `fn` at `avals` (ShapeDtypeStructs or arrays) and analyze
    the jaxpr — the front door for fused-step cores and plain jax
    functions."""
    import jax
    closed = jax.make_jaxpr(fn)(*avals)
    return analyze_jaxpr(closed, name=name or getattr(fn, "__name__",
                                                      "callable"),
                         profile=profile, donated=donate_argnums)


def analyze_executor(exe, name=None, profile=None, is_train=False):
    """Analyze a bound `Executor`'s whole-graph program (the jaxpr the
    forward jit compiles): control-flow subgraphs cost their true
    scan-body work (body flops x trip count), which the symbol-side
    walk cannot see through a `_foreach` node."""
    import jax
    fn = exe._graph_fn(bool(is_train))
    args = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
            for a in exe.arg_arrays]
    aux = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
           for a in exe.aux_arrays]
    key = jax.ShapeDtypeStruct((2,), _np.uint32)
    return analyze_callable(lambda a, x, k: fn(a, x, k),
                            [args, aux, key],
                            name=name or "executor", profile=profile)


# ---------------------------------------------------------------------------
# collective enumeration (the kvstore/pod plan, statically)
# ---------------------------------------------------------------------------

def enumerate_collectives(shapes, dtypes=None, dp=8, cap_bytes=None,
                          order=None, extras=False, name=None):
    """Statically derive one training step's gradient-exchange economy
    for a dp-way mesh: the bucket plan (THE shared `kvstore.plan_buckets`
    rule, default priority order = reversed parameter order exactly as
    the scheduler and the pod fast path plan it), collectives per step,
    payload bytes per step (the number `KVStore.stats()['bytes_reduced']`
    measures), and the ring-model bytes each chip moves on the ICI.

    ``extras=True`` models the pod fast path's bundled extras psum: it
    folds into the first f32 bucket when one exists, else costs one
    extra collective.
    """
    shapes = list(shapes)
    n = len(shapes)
    if dtypes is None:
        dtypes = [_np.dtype("float32")] * n
    dtypes = [_np.dtype(d) if not isinstance(d, _np.dtype) else d
              for d in dtypes]
    if cap_bytes is None:
        from .. import config as _config
        cap_bytes = max(1, int(
            float(_config.get("MXNET_KVSTORE_BUCKET_MB")) * (1 << 20)))
    sizes = [(int(_np.prod(s)) if s else 1) * dt.itemsize
             for s, dt in zip(shapes, dtypes)]
    if order is None:
        order = list(reversed(range(n)))
    from ..kvstore import plan_buckets
    plan = plan_buckets(order, sizes, dtypes, cap_bytes)
    total = sum(sizes)
    collectives = len(plan)
    if extras and not any(dtypes[b[0]] == _np.dtype("float32")
                          for b in plan):
        collectives += 1
    # ideal plan size: dtype grouping + the size cap (the economy the
    # scheduler promises; O(params) single-item buckets break it)
    ndt = len({dt.name for dt in dtypes})
    ideal = max(1, int(math.ceil(total / cap_bytes))) + ndt - 1
    o_params = n > 2 and len(plan) >= n and len(plan) > 2 * ideal
    return {
        "name": name or "plan",
        "dp": int(dp),
        "params": n,
        "total_param_bytes": int(total),
        "bucket_cap_mb": cap_bytes / (1 << 20),
        "buckets": len(plan),
        "collectives_per_step": int(collectives),
        "bytes_per_step": int(total),
        "ici_bytes_per_chip": int(2 * (dp - 1) / max(1, dp) * total),
        "pull_broadcasts": len(plan),
        "dispatch_complexity": "O(params)" if o_params else "O(buckets)",
        "plan": [list(b) for b in plan],
    }


def collectives_report(stats, target=None):
    """Findings view of `enumerate_collectives` output."""
    report = Report(target=target or stats.get("name"))
    report.add(Finding(
        "cost.collectives", "collective-summary", HINT,
        "%s: dp=%d, %d param(s) -> %d bucket(s), %d collective(s)/step, "
        "%.2f MB/step payload (%.2f MB on the ICI per chip), %s dispatch"
        % (stats["name"], stats["dp"], stats["params"], stats["buckets"],
           stats["collectives_per_step"],
           stats["bytes_per_step"] / (1 << 20),
           stats["ici_bytes_per_chip"] / (1 << 20),
           stats["dispatch_complexity"]),
        location=stats.get("name")))
    if stats["dispatch_complexity"] == "O(params)":
        report.add(Finding(
            "cost.collectives", "collective-o-params", WARN,
            "%s: the plan dispatches %d collectives for %d params "
            "(every bucket single-item; ~%d would satisfy the %g MB "
            "cap): per-parameter dispatch is the pod-scale throughput "
            "killer the bucketed scheduler exists to prevent — check "
            "the push ordering/dtype interleaving"
            % (stats["name"], stats["collectives_per_step"],
               stats["params"],
               max(1, int(math.ceil(stats["total_param_bytes"] /
                                    (stats["bucket_cap_mb"] *
                                     (1 << 20))))),
               stats["bucket_cap_mb"]),
            location=stats.get("name")))
    return report


# ---------------------------------------------------------------------------
# the canonical bench program set (shared with tools/bench_ops.py and
# the mxlint --cost-report default)
# ---------------------------------------------------------------------------

BENCH_SHAPE = (8, 3, 32, 32)


def build_bench_convnet(dtype="float32"):
    """The BENCH_OPS quantization-battery convnet (conv3x3/16 + relu +
    maxpool + flatten + fc32), with every variable declared at `dtype`
    so the bf16 variant is bf16 end to end.  Returns (symbol, shapes)."""
    from .. import sym as S
    kw = {} if dtype == "float32" else {"dtype": dtype}
    # weight shapes are declared on the variables: a declared non-f32
    # dtype only takes effect in abstract evaluation when the shape is
    # known too (the param-shape solver would otherwise re-seed f32)
    c, hw = BENCH_SHAPE[1], BENCH_SHAPE[2]
    data = S.Variable("data", shape=BENCH_SHAPE, **kw)
    x = S.Convolution(data,
                      S.Variable("conv0_weight", shape=(16, c, 3, 3),
                                 **kw),
                      S.Variable("conv0_bias", shape=(16,), **kw),
                      kernel=(3, 3), num_filter=16, pad=(1, 1),
                      name="conv0")
    x = S.Activation(x, act_type="relu", name="relu0")
    x = S.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                  name="pool0")
    x = S.Flatten(x, name="flatten0")
    fc_in = 16 * (hw // 2) * (hw // 2)
    out = S.FullyConnected(x,
                           S.Variable("fc0_weight", shape=(32, fc_in),
                                      **kw),
                           S.Variable("fc0_bias", shape=(32,), **kw),
                           num_hidden=32, name="fc0")
    return out, {"data": BENCH_SHAPE}


def build_bench_quantized_convnet():
    """quantize_model over the fp32 bench convnet — THE int8 graph
    BENCH_OPS times (same rewrite, same rng seed for the weights).
    Returns (qsym, shapes, dtypes) where dtypes carries the int8 weight
    dtypes the variable attrs cannot."""
    import numpy as np
    from .. import nd
    from ..contrib.quantization import quantize_model

    sym, shapes = build_bench_convnet("float32")
    rng = np.random.RandomState(2)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=BENCH_SHAPE)
    args = {n: nd.array(rng.normal(0, 0.5, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}
    auxs = {n: nd.zeros(s) for n, s in
            zip(sym.list_auxiliary_states(), aux_shapes)}
    qsym, qargs, _ = quantize_model(sym, args, auxs, calib_mode="none")
    dtypes = {n: str(a.dtype) for n, a in qargs.items()}
    return qsym, shapes, dtypes


def bench_programs():
    """{name: (symbol, shapes, dtypes)} — the program set the budget
    baseline covers.  Names match the BENCH_OPS artifact keys."""
    fp32, shapes = build_bench_convnet("float32")
    bf16, _ = build_bench_convnet("bfloat16")
    qsym, qshapes, qdtypes = build_bench_quantized_convnet()
    return {
        "quantization.convnet_fp32": (fp32, shapes, None),
        "quantization.convnet_bf16": (bf16, shapes, None),
        "quantization.convnet_int8": (qsym, qshapes, qdtypes),
    }


def analyze_bench_set(profile=None, dp=8, cap_bytes=None):
    """Analyze the canonical bench set + the dp-way collective plan for
    its fp32 params: {name: ProgramCost}, plus the plan stats under the
    key ``__collectives__``.  This is what the mxlint --cost-report
    default run, the parity `cost` stage, and the budget baseline all
    share."""
    out = {}
    for name, (sym, shapes, dtypes) in sorted(bench_programs().items()):
        out[name] = analyze_symbol(sym, shapes=shapes, dtypes=dtypes,
                                   profile=profile, target=name)
    fp32, shapes = build_bench_convnet("float32")
    arg_shapes, _, _ = fp32.infer_shape(data=BENCH_SHAPE)
    pshapes = [s for n, s in zip(fp32.list_arguments(), arg_shapes)
               if n != "data"]
    stats = enumerate_collectives(pshapes, dp=dp, cap_bytes=cap_bytes,
                                  name="dp%d_bucketed_convnet" % dp)
    out["__collectives__"] = stats
    return out


# ---------------------------------------------------------------------------
# sparse embedding cost model (mxembed)
# ---------------------------------------------------------------------------

# flops per touched element for the lazy row-sparse update paths
# (optimizer.py _lazy_*_jit): rescale + clip + wd fold, then the
# update math; adam adds two moment EMAs, a square, a sqrt and a divide
_EMBED_UPDATE_FLOPS = {"lookup": 0, "scatter": 0, "sgd": 4,
                       "sgd_momentum": 7, "adam": 14}

# optimizer state rows moved per touched row (read + write each):
# momentum keeps one slot, adam two
_EMBED_STATE_ROWS = {"lookup": 0, "scatter": 0, "sgd": 0,
                     "sgd_momentum": 1, "adam": 2}


def analyze_embedding(num_rows, dim, rows_touched, dtype="float32",
                      kind="lookup", profile=None, name=None):
    """Static cost of one sparse-embedding op: the rows-touched x
    row-bytes model.

    The sparse path is host/wire-resident (ndarray/sparse.py design
    note), so there is no traced program to walk — but its cost is
    exactly determined by how many rows move: a ``lookup`` gathers
    ``rows_touched`` rows of ``dim * itemsize`` bytes (plus the int64
    id vector) and writes them back out; a ``scatter`` writes them; the
    optimizer kinds (``sgd``/``sgd_momentum``/``adam``) additionally
    read-modify-write the touched weight rows, the gradient rows, and
    the optimizer's state rows, at the lazy kernels' per-element flop
    counts.  Everything off the touched rows is free — that is the whole
    point of the lazy contract."""
    if kind not in _EMBED_UPDATE_FLOPS:
        raise ValueError(f"analyze_embedding: unknown kind {kind!r} "
                         f"(one of {sorted(_EMBED_UPDATE_FLOPS)})")
    profile = get_profile(profile)
    prog = ProgramCost(name or f"embedding.{kind}", profile)
    k = int(rows_touched)
    d = int(dim)
    isize = _np.dtype(dtype).itemsize
    row_bytes = d * isize
    idx_bytes = k * 8
    flops = _EMBED_UPDATE_FLOPS[kind] * k * d
    if kind == "lookup":
        bytes_in, bytes_out = k * row_bytes + idx_bytes, k * row_bytes
    elif kind == "scatter":
        bytes_in, bytes_out = k * row_bytes + idx_bytes, k * row_bytes
    else:
        state = _EMBED_STATE_ROWS[kind]
        # read: weight rows + grad rows + state rows + ids;
        # write: weight rows + state rows
        bytes_in = (2 + state) * k * row_bytes + idx_bytes
        bytes_out = (1 + state) * k * row_bytes
    dt = _dtype_key(dtype)
    bound = _classify(f"embedding.{kind}", flops, bytes_in + bytes_out,
                      dt, profile)
    prog.per_op.append(OpCost(
        node=f"embedding.{kind}", op=f"embedding.{kind}", flops=flops,
        bytes_in=bytes_in, bytes_out=bytes_out, compute_dtype=dt,
        ai=flops / max(1, bytes_in + bytes_out), bound=bound))
    prog.param_bytes = int(num_rows) * row_bytes
    return prog
