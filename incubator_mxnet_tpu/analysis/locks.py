"""The audited lock-construction idiom (`mxtsan`'s instrumentation shims).

Every lock, rlock, and condition in this codebase is built through this
module instead of `threading` directly::

    from ..analysis import locks as _locks
    self._lock = _locks.make_lock("serving.batcher")
    self._cond = _locks.make_condition(name="dist.membership")

With ``MXNET_TSAN`` unset (the default) each factory returns the plain
`threading` object — byte-identical hot paths, zero overhead, nothing
imported beyond this three-function module.  With the sanitizer on
(``MXNET_TSAN=1`` or `analysis.tsan.enable()`) the factories return
`tsan` wrappers that feed the process-wide lock-acquisition-order graph
(deadlock detection), the per-access locksets (race attribution), and
the contended-lock set (blocking-call findings).

The `name` is the lock's node in the order graph; instances constructed
with the same name share a node (a pool of per-request locks is one
hazard class, not ten thousand).  Name by subsystem:
``"serving.router"``, ``"dist.membership"``, ``"compile.cache"``.
"""
from __future__ import annotations

import threading

__all__ = ["make_lock", "make_rlock", "make_condition"]


def make_lock(name=None):
    """A `threading.Lock`, instrumented when the sanitizer is on."""
    from . import tsan
    if tsan.enabled():
        return tsan.TsanLock(name)
    return threading.Lock()


def make_rlock(name=None):
    """A `threading.RLock`, instrumented when the sanitizer is on."""
    from . import tsan
    if tsan.enabled():
        return tsan.TsanRLock(name)
    return threading.RLock()


def make_condition(lock=None, name=None):
    """A `threading.Condition`.  Pass a lock built by `make_lock` to
    share it (the batcher's lock+condition pair), or just a `name` for a
    standalone condition whose internal lock joins the order graph."""
    from . import tsan
    if tsan.enabled():
        return tsan.make_condition(lock=lock, name=name)
    return threading.Condition(lock)
