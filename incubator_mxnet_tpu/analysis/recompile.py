"""Recompilation audit: every new jit signature, and why it is new.

An XLA compile of the fused train step costs seconds to minutes; a shape
that churns (the classic: a ragged final batch without padding) pays it
every epoch and looks like random multi-second stalls.  The fused paths
report each dispatch signature here; the auditor records the history per
program and, when a NEW signature arrives, diffs it against the previous
one and emits a finding naming the exact argument that changed — plus a
ragged-batch diagnosis when only the leading (batch) dimension moved.

Recording is unconditional (a tuple compare per dispatch in the steady
state); findings surface through `analysis.runtime_report()`.
"""
from __future__ import annotations

from .findings import Finding, WARN
from . import locks as _locks

__all__ = ["note", "register", "findings", "signatures", "reset",
           "CODES"]

# every code this auditor emits (the findings.CODE_TABLE cross-check)
CODES = ("shape-churn",)

_lock = _locks.make_lock("analysis.recompile")
_seen = {}       # key -> list of signatures in first-seen order
_findings = []
_MAX_SIGS = 64   # per program; beyond this something is deeply wrong
_MAX_FINDINGS = 256


def _diff(names, prev, sig):
    """Describe which args changed between two signatures."""
    changed = []
    batch_only = True
    for i, (old, new) in enumerate(zip(prev, sig)):
        if old == new:
            continue
        name = names[i] if names and i < len(names) else f"arg{i}"
        (oshape, odt), (nshape, ndt) = old, new
        if odt != ndt:
            changed.append(f"'{name}' dtype {odt} -> {ndt}")
            batch_only = False
        else:
            changed.append(f"'{name}' shape {tuple(oshape)} -> "
                           f"{tuple(nshape)}")
            same_tail = (len(oshape) == len(nshape) and
                         tuple(oshape[1:]) == tuple(nshape[1:]))
            if not same_tail:
                batch_only = False
    if len(prev) != len(sig):
        changed.append(f"arg count {len(prev)} -> {len(sig)}")
        batch_only = False
    return changed, batch_only and bool(changed)


def note(key, names, sig):
    """Report one dispatch of program `key` with input signature `sig`
    (a tuple of (shape, dtype) per arg, `names` naming the args).
    Returns the Finding emitted for a churned signature, else None."""
    sig = tuple(sig)
    with _lock:
        hist = _seen.get(key)
        if hist is None:
            _seen[key] = [sig]
            return None
        if sig == hist[-1] or sig in hist:
            return None
        prev = hist[-1]
        if len(hist) < _MAX_SIGS:
            hist.append(sig)
    changed, batch_only = _diff(names, prev, sig)
    detail = "; ".join(changed[:6]) or "signature changed"
    hint = (" — looks like a ragged final batch; pad or discard the tail "
            "(NDArrayIter last_batch_handle='pad'/'discard') so one "
            "compiled program serves every step" if batch_only else "")
    f = Finding(
        "trace.recompile", "shape-churn", WARN,
        f"{key}: new jit signature #{len(_seen[key])} forces a fresh XLA "
        f"compile: {detail}{hint}",
        location=key)
    with _lock:
        if len(_findings) < _MAX_FINDINGS:
            _findings.append(f)
    return f


def register(key, names, sig):
    """Pre-declare an EXPECTED signature for program `key` without a
    shape-churn finding — the serving runtime's warmup path registers every
    bucket it compiles up front, so only post-warmup novelty (a request
    shape no bucket covers) surfaces as churn.  `names` is accepted for
    symmetry with `note` (the later diff uses the noted names)."""
    del names
    sig = tuple(sig)
    with _lock:
        hist = _seen.setdefault(key, [])
        if sig not in hist and len(hist) < _MAX_SIGS:
            hist.append(sig)


def signatures(key):
    """The distinct signatures recorded for a program (oldest first)."""
    with _lock:
        return list(_seen.get(key, ()))


def findings():
    with _lock:
        return list(_findings)


def reset():
    with _lock:
        _seen.clear()
        del _findings[:]
