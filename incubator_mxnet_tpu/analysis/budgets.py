"""Cost budgets: the committed baseline that turns mxcost regressions
into hard CI failures.

``COST_BUDGETS.json`` (repo root) records, per program in the canonical
bench set, the statically-derived flops / bytes-moved / peak-HBM numbers
and the dtype-flow defect counters (dequant chains, fp32-compute
quantized ops, f32 upcasts, hidden host transfers), plus the collective
economy of the dp-8 bucketed plan.  `check()` compares a fresh analysis
against the baseline:

* a counter above budget, a new collective, +bytes/step or +peak-HBM
  beyond tolerance  -> **ERROR** ``budget-regression`` (CI fails);
* a metric meaningfully below budget -> **HINT** ``budget-slack`` (an
  improvement landed: re-snapshot so the gate tightens behind it);
* a program with no baseline entry -> **HINT** ``budget-missing``.

Known, budgeted defects stay visible but do not fail CI: a WARN finding
whose counter is within budget is demoted to HINT ("budgeted"), so
``mxlint --cost-report --fail-on=warn`` passes on HEAD while any NEW
dequant chain / upcast / collective fails the build.  The workflow:

    python tools/mxlint.py --cost-report --budgets COST_BUDGETS.json
    # regress -> exit 1; improve -> budget-slack hints
    python tools/mxlint.py --cost-report --write-budgets COST_BUDGETS.json
    # re-baseline after an intentional change (commit the diff)
"""
from __future__ import annotations

import json

from .findings import Finding, Report, ERROR, WARN, HINT

__all__ = ["snapshot", "load", "save", "check", "DEFAULT_TOLERANCES",
           "CODES", "MEASURED_TOLERANCES", "snapshot_measured",
           "check_measured"]

# every code the budget gate emits (the findings.CODE_TABLE cross-check)
CODES = ("budget-regression", "budget-missing", "budget-slack")

# relative headroom for the continuous metrics; counters are exact
DEFAULT_TOLERANCES = {
    "flops": 0.05,
    "bytes_moved": 0.10,
    "peak_hbm_bytes": 0.10,
    "param_bytes": 0.05,
    "bytes_per_step": 0.10,
}

# measured (wall-clock / runtime-reported) metrics: only the keys
# listed HERE are gated — everything else the coldstart probe records
# (lower_s, trace_s, the pure-JAX control's own timings) is
# informational.  compile_s wall time varies with host load, so it gets
# wide headroom; peak_hbm_mb is the 15% envelope around the mxcost
# liveness prediction the baseline commits; jaxpr_eqns and the
# fused-vs-pure-JAX compile ratio are exact caps.
MEASURED_TOLERANCES = {
    "compile_s": 0.50,
    "peak_hbm_mb": 0.15,
    "jaxpr_eqns": 0.0,
    "compile_ratio_vs_jax": 0.0,
}

# snapshot floors: a measured value below the floor commits the FLOOR
# as the budget, so the gate stays the contract cap (fused-step compile
# <= 1.5x pure JAX) rather than chasing a lucky measurement down, and
# sub-second CPU compile times gate order-of-magnitude blowups instead
# of scheduler noise
_SNAPSHOT_FLOORS = {"compile_ratio_vs_jax": 1.5, "compile_s": 0.5}

# exact counters a program budget carries, and the finding code each one
# licenses (within budget -> that code's WARNs demote to HINT)
_COUNTER_CODES = {
    "dequant_fp32_dot": "dequant-fp32-dot",
    "quantized_fp32_compute": "quantized-fp32-compute",
    "f32_upcasts": "f32-upcast-in-bf16",
    "host_transfers": "hidden-host-transfer",
}
_SCALARS = ("flops", "bytes_moved", "peak_hbm_bytes", "param_bytes")
_COLL_COUNTERS = ("collectives_per_step", "buckets", "pull_broadcasts")


def snapshot(results):
    """Budget dict from an `analyze_bench_set`-style result map
    ({name: ProgramCost, '__collectives__': stats})."""
    budgets = {"version": 1, "tolerances": dict(DEFAULT_TOLERANCES),
               "programs": {}, "collectives": {}}
    for name, prog in sorted(results.items()):
        if name == "__collectives__":
            st = prog
            budgets["collectives"][st["name"]] = {
                "dp": st["dp"], "params": st["params"],
                "collectives_per_step": st["collectives_per_step"],
                "buckets": st["buckets"],
                "pull_broadcasts": st["pull_broadcasts"],
                "bytes_per_step": st["bytes_per_step"],
                "dispatch_complexity": st["dispatch_complexity"],
            }
            continue
        d = prog.as_dict()
        entry = {k: d[k] for k in _SCALARS if d.get(k) is not None}
        entry.update(d["counters"])
        budgets["programs"][name] = entry
    return budgets


def load(path):
    with open(path, encoding="utf-8") as f:
        budgets = json.load(f)
    if not isinstance(budgets, dict) or "programs" not in budgets:
        raise ValueError(f"{path}: not a COST_BUDGETS file "
                         "(no 'programs' table)")
    return budgets


def save(path, budgets):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")


def snapshot_measured(measured, budgets=None):
    """Fold a {program: {metric: value}} map of MEASURED numbers (the
    coldstart probe's compile_s / peak_hbm_mb) into a budget dict's
    'measured' section, returning the dict.  Unlike the static
    `snapshot`, this merges: programs not re-measured keep their
    committed entries."""
    if budgets is None:
        budgets = {"version": 1, "tolerances": dict(DEFAULT_TOLERANCES),
                   "programs": {}, "collectives": {}}
    section = budgets.setdefault("measured", {})
    budgets.setdefault("measured_tolerances", dict(MEASURED_TOLERANCES))
    for name, metrics in sorted(measured.items()):
        entry = section.setdefault(name, {})
        for k, v in sorted(metrics.items()):
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            entry[k] = round(max(float(v), _SNAPSHOT_FLOORS.get(k, 0.0)),
                             4)
    return budgets


def check_measured(measured, budgets):
    """Compare a {program: {metric: value}} map of measured coldstart
    numbers against the budget dict's 'measured' section.  Same finding
    codes and (report, deltas) contract as `check`."""
    report = Report(target="coldstart-budgets")
    deltas = {}
    tol = dict(MEASURED_TOLERANCES)
    tol.update(budgets.get("measured_tolerances") or {})
    baseline = budgets.get("measured") or {}
    for name, metrics in sorted(measured.items()):
        b = baseline.get(name)
        if b is None:
            report.add(Finding(
                "cost.budget", "budget-missing", HINT,
                "program '%s' has no measured baseline entry — snapshot "
                "it (run_tpu_parity coldstart stage --write-budgets) so "
                "cold-start regressions become CI failures" % name,
                location=name))
            continue
        for metric, value in sorted(metrics.items()):
            if metric not in tol or \
                    not isinstance(value, (int, float)) or \
                    isinstance(value, bool):
                continue   # informational metric: recorded, not gated
            # a budget pinned at its snapshot floor is a contract cap,
            # not a measurement — running under it is not "slack"
            floor = _SNAPSHOT_FLOORS.get(metric)
            pinned = floor is not None and b.get(metric) == floor
            _compare(report, deltas, name, metric, value,
                     b.get(metric), tol[metric], slack=not pinned)
    return report, deltas


def _compare(report, deltas, scope, metric, value, budget, tol,
             slack=True):
    """One metric against its budget; returns True when in budget."""
    if value is None or budget is None:
        return True
    entry = {"value": value, "budget": budget, "ok": True}
    deltas.setdefault(scope, {})[metric] = entry
    if budget:
        entry["delta_pct"] = round(100.0 * (value - budget) / budget, 2)
    if value > budget * (1.0 + tol):
        entry["ok"] = False
        delta = "%+.1f%%" % entry["delta_pct"] if budget else \
            "was zero"   # a percentage of a 0 budget is meaningless
        report.add(Finding(
            "cost.budget", "budget-regression", ERROR,
            "%s: %s regressed to %s over budget %s (%s, tolerance "
            "%.0f%%) — a perf PR must either stay inside the committed "
            "budget or intentionally re-baseline COST_BUDGETS.json "
            "(mxlint --cost-report --write-budgets)"
            % (scope, metric, _fmt(value), _fmt(budget), delta,
               100 * tol),
            location=scope))
        return False
    band = tol if tol else 0.0
    if slack and (value < budget * (1.0 - max(band, 0.05)) or
                  (tol == 0.0 and value < budget)):
        report.add(Finding(
            "cost.budget", "budget-slack", HINT,
            "%s: %s improved to %s, well under budget %s — re-snapshot "
            "COST_BUDGETS.json so the gate tightens behind the win"
            % (scope, metric, _fmt(value), _fmt(budget)),
            location=scope))
    return True


def _fmt(v):
    if isinstance(v, float) and not v.is_integer():
        return "%.4g" % v
    v = int(v)
    if v >= (1 << 20):
        return "%.2f MB" % (v / (1 << 20))
    return str(v)


def check(results, budgets):
    """Compare {name: ProgramCost, '__collectives__': stats} against a
    budget dict.  Returns (report, deltas):

    * `report` carries the budget findings AND every program finding,
      with in-budget WARNs demoted to HINT ("budgeted") — feed it to
      the CLI severity gate;
    * `deltas` is the per-program {metric: {value, budget, delta_pct,
      ok}} map the parity artifact records.
    """
    report = Report(target="cost-budgets")
    deltas = {}
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(budgets.get("tolerances") or {})
    prog_budgets = budgets.get("programs") or {}
    coll_budgets = budgets.get("collectives") or {}

    for name, prog in sorted(results.items()):
        if name == "__collectives__":
            st = prog
            b = coll_budgets.get(st["name"])
            if b is None:
                report.add(Finding(
                    "cost.budget", "budget-missing", HINT,
                    "collective plan '%s' has no baseline entry — "
                    "snapshot it so new collectives become regressions"
                    % st["name"], location=st["name"]))
                continue
            for metric in _COLL_COUNTERS:
                _compare(report, deltas, st["name"], metric,
                         st.get(metric), b.get(metric), 0.0)
            _compare(report, deltas, st["name"], "bytes_per_step",
                     st.get("bytes_per_step"), b.get("bytes_per_step"),
                     tol["bytes_per_step"])
            if st.get("dispatch_complexity") == "O(params)" and \
                    b.get("dispatch_complexity") != "O(params)":
                report.add(Finding(
                    "cost.budget", "budget-regression", ERROR,
                    "%s: dispatch complexity regressed to O(params) "
                    "(every bucket single-item) from the budgeted "
                    "O(buckets) economy" % st["name"],
                    location=st["name"]))
            continue

        d = prog.as_dict()
        b = prog_budgets.get(name)
        if b is None:
            report.add(Finding(
                "cost.budget", "budget-missing", HINT,
                "program '%s' has no baseline entry in the budget file "
                "— snapshot it (mxlint --cost-report --write-budgets) "
                "so regressions become CI failures" % name,
                location=name))
            report.extend(prog.report)
            continue
        in_budget_codes = set()
        for counter, code in _COUNTER_CODES.items():
            ok = _compare(report, deltas, name, counter,
                          d["counters"].get(counter, 0),
                          b.get(counter, 0), 0.0)
            if ok:
                in_budget_codes.add(code)
        for metric in _SCALARS:
            _compare(report, deltas, name, metric, d.get(metric),
                     b.get(metric), tol.get(metric, 0.1))
        # known, budgeted defects stay visible but do not fail CI
        for f in prog.report:
            if f.severity == WARN and f.code in in_budget_codes:
                demoted = Finding(f.pass_name, f.code, HINT,
                                  f.message + " [budgeted: within the "
                                  "committed COST_BUDGETS baseline]",
                                  node=f.node, location=f.location)
                demoted.count = f.count
                report.add(demoted)
            else:
                report.add(f)
    return report, deltas
