"""Use-after-donation tracking.

The fused train paths donate every persistent buffer (weights, optimizer
state, aux states) to the XLA program each step — after dispatch the OLD
`jax.Array`s are deleted, and any read through a stale NDArray used to
die as an opaque PJRT "Array has been deleted" error (or was only caught
by the ad-hoc probe this module replaces, formerly
`fused._donated_invalidated`).  This tracker gives those failures names:

* `record(...)` registers the donated leaves of each named pytree with
  the step that consumed them (weakrefs — deleted arrays are still live
  Python objects, so the registry entry survives exactly as long as the
  stale wrapper that could be misread);
* `explain(arr)` answers "whose buffer was this, and which step ate it";
* `consumed(...)` / `raise_if_consumed(...)` are the post-dispatch triage
  used by the fused paths: when a failed dispatch already consumed the
  buffers, falling back to eager would replay onto deleted arrays — the
  error must name the parameter, not fall back.

Registration of every step's ~N·leaves is gated on `analysis.enabled()`
(MXNET_ANALYSIS=1); the translation of deleted-buffer reads into
`MXNetError` is always on (it costs nothing on the happy path — the
check runs only inside exception handlers).
"""
from __future__ import annotations

import weakref

from ..base import MXNetError
from . import locks as _locks

__all__ = ["record", "explain", "consumed", "raise_if_consumed",
           "any_deleted", "is_deleted"]

_lock = _locks.make_lock("analysis.donation")
# id(jax.Array) -> (weakref to the array, owner name, step description).
# The weakref's callback removes the entry, so ids never dangle onto a
# recycled object.
_registry = {}


def _leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def is_deleted(arr):
    """True when `arr` is a jax array whose buffer a donation consumed."""
    try:
        fn = getattr(arr, "is_deleted", None)
        return bool(fn and fn())
    except Exception:
        return False


def record(step_desc, named_trees):
    """Register the leaves of each (owner_name, pytree) as donated by
    `step_desc` (e.g. ``"FusedTrainStep step 42"``)."""
    with _lock:
        for name, tree in named_trees:
            for leaf in _leaves(tree):
                key = id(leaf)
                try:
                    ref = weakref.ref(
                        leaf, lambda _r, _k=key: _registry.pop(_k, None))
                except TypeError:
                    continue  # not weakref-able (host numpy buffer etc.)
                _registry[key] = (ref, name, step_desc)


def explain(arr):
    """Human message for a deleted buffer: the owning parameter and the
    consuming step when tracked, generic donation guidance otherwise.
    Returns None when `arr` is not a deleted jax array."""
    if not is_deleted(arr):
        return None
    with _lock:
        rec = _registry.get(id(arr))
        rec = rec if rec is not None and rec[0]() is arr else None
    if rec is not None:
        _, name, step_desc = rec
        return (f"use-after-donation: the buffer of '{name}' was donated "
                f"to {step_desc} and no longer holds data. Read current "
                "values through the public APIs (Module.get_params / "
                "get_outputs, Trainer), which flush the fused step's "
                "pending results first.")
    return ("use-after-donation: this buffer was deleted, most likely by "
            "donation to a fused XLA train step. Read current values "
            "through the public APIs (Module.get_params / get_outputs, "
            "Trainer), which flush pending fused results first; set "
            "MXNET_ANALYSIS=1 to track donations by parameter name.")


def any_deleted(*trees):
    """True when any jax-array leaf in the given pytrees was deleted by a
    donating dispatch (the probe formerly at fused._donated_invalidated)."""
    for t in trees:
        for leaf in _leaves(t):
            if is_deleted(leaf):
                return True
    return False


def consumed(named_trees):
    """Names whose pytree contains at least one donated-and-deleted leaf."""
    hit = []
    for name, tree in named_trees:
        if any(is_deleted(leaf) for leaf in _leaves(tree)):
            hit.append(name)
    return hit


def raise_if_consumed(kind, exc, named_trees):
    """Post-dispatch failure triage for the fused paths: when the donating
    dispatch already consumed persistent buffers, raise an `MXNetError`
    NAMING them (an eager fallback would replay onto deleted arrays and
    leave training state unrecoverable).  Returns when a fallback is safe
    (all buffers intact)."""
    names = consumed(named_trees)
    if names:
        shown = ", ".join(repr(n) for n in names[:8])
        more = f" (+{len(names) - 8} more)" if len(names) > 8 else ""
        raise MXNetError(
            f"{kind} failed AFTER its donating dispatch consumed the "
            f"buffers of {shown}{more}; training state is unrecoverable — "
            f"restart from a checkpoint (cause: {str(exc)[:300]})") from exc
