"""AST lints over example/training scripts.

The runtime host-sync detector (hostsync.py) catches blocking reads
while they happen; this pass catches them BEFORE anything runs, by
walking a script's AST:

* ``host-sync-in-loop`` — `.asnumpy()` / `.asscalar()` / `.item()` /
  `.wait_to_read()` / `waitall()` lexically inside a `for`/`while` body:
  the classic TPU throughput killer (each call serializes the host with
  the device once per iteration).
* ``kvstore-local-on-tpu`` — a literal ``kvstore='local'`` passed to
  `fit`/`init_optimizer`/`Trainer` in a script that also creates TPU
  contexts: 'local' stages gradient reduction through host memory; on
  TPU the reduce should ride ICI collectives (``kvstore='device'`` or
  ``'tpu'``).
* ``unbounded-retry`` — a ``while True`` loop whose try/except swallows
  a connect/request/recv failure, with no deadline reference and no
  `raise`: the classic "retry until the scheduler is up" loop that
  spins forever against a PERMANENTLY dead peer.  Bound it with a
  monotonic deadline or `resilience.RetryPolicy`.  (A bare call with no
  try is fine — the exception escaping the loop is a bound.)
* ``bare-except`` — a bare ``except:`` with no re-raise (or an
  ``except Exception:`` whose body only passes/continues): it swallows
  `MXNetError` — including structured failover signals like
  `ServerLostError` — and the training script keeps "running" on a dead
  cluster.
* ``router-bypass`` — a direct `ServedModel.infer()` call or a bare
  `ModelServer(...)` in a script that also configures a
  `ReplicaRouter`: traffic through those paths bypasses the router's
  failover, health checking, and priority-class shedding — one replica
  death or one overload burst takes exactly that traffic down.  Route
  requests through ``router.submit()/predict()`` (or keep the script
  router-less on purpose and say so with a suppression).
* ``unguarded-model-swap`` — a direct `swap_weights()` /
  `replica.swap()` call in a script that also constructs a
  `LoopController`: pushing weights straight onto the fleet bypasses
  the canary gate the script itself set up — one bad checkpoint goes
  straight to 100% of traffic with no holdout score and no rejected
  stamp.  Publish the checkpoint to the `ModelRegistry` and let
  `LoopController.poll_once()` canary it before the rolling swap.
* ``fixed-fleet`` — a `ReplicaRouter` constructed with a hand-rolled
  FIXED replica list (a list/tuple literal or a comprehension of
  replica constructors) in a script that also configures the fleet
  autoscaler (`FleetManager` / an `Autoscaler`): the fleet layer owns
  membership — it places replicas across hosts with anti-affinity,
  backfills host losses, and scales on the SLO signal — so a
  hand-pinned fleet silently caps capacity at whatever the script
  hard-coded and leaves host placement to luck.  Hand the router (or
  nothing: the manager builds its own) plus the host registry to
  `FleetManager` and let placement spawn the replicas.
* ``nan-swallow`` — a ``try`` whose body runs a training update
  (`Module.fit` / `fit_step` / a trainer's ``.step``) with an
  exception handler that swallows the failure and keeps looping
  (optionally after an ``isnan``/``isfinite`` check): the classic
  hand-rolled "skip the NaN batch and hope" pattern.  It silently
  loses steps, desynchronizes multi-worker runs, and leaves no
  quarantine trail — the training guardian (MXNET_GUARDIAN,
  resilience/guardian.py) does this correctly: in-graph skip with
  deterministic RNG/optimizer advance, loss-spike rollback, and a
  quarantine log.
* ``unbucketed-push`` — a per-parameter ``kv.push``/``kv.pull`` inside
  a training loop (the key is derived from the loop variable): the
  collective stores advertise ``prefers_batched_push`` — one batched
  push/pull of the FULL key list reduces in O(buckets) overlapped
  all-reduce collectives, while the per-parameter loop dispatches one
  collective per key (the classic pod-scale throughput killer).  Pass
  the whole key list in one call (``kv.push(names, grads)``), or
  stream with ``begin_push``/``push_part``/``end_push``.
* ``host-transfer-in-graph`` — a host coercion (`.asnumpy()` /
  `.asscalar()` / `.item()` / `np.asarray` / `np.array` /
  `jax.device_get`) lexically inside a jit/pjit/shard_map-decorated
  function: the traced program either fails to trace or (via a
  callback) crosses to the host on EVERY step — the mxcost jaxpr pass
  (`hidden-host-transfer`) is the runtime-graph side of the same
  hazard.  Move the computation in-graph or hoist the read out of the
  traced region.
* ``blocking-h2d-in-loop`` — a direct host→device feed
  (`jax.device_put` / `.as_in_context(...)`) lexically inside a
  TRAINING loop (one whose body also runs `fit`/`fit_step`/
  `forward_backward`/a trainer's ``.step``): the transfer serializes
  with the step it feeds — the 13.8 MB/s h2d failure mode.  The
  prefetch ring (``MXNET_IO_RING``, `io_plane.DevicePrefetchIter`)
  stages and transfers batches on the ``mx-io-h2d`` thread with
  device-resident prefetch; feed the loop from it instead.
* ``unsupervised-collective`` — a host-level cross-host collective
  dispatch (`collectives.all_reduce` / `all_gather` / `reduce_scatter` /
  `ppermute` / a collective plane's `allreduce`) outside a supervisor/
  watchdog scope: on a pod, one lost host hangs that call forever with
  no error.  Wrap it with `parallel.collectives.supervised(...)`, run it
  under a `JobSupervisor`, or put it in a ``with``-scope whose manager
  names the supervisor/watchdog.  In-graph uses (inside a
  jit/pjit/shard_map-decorated function) are XLA's business and are not
  flagged.

Concurrency lints (the static half of the mxtsan tier; ``mxlint
--tsan-report`` runs exactly this subset over the package):

* ``unnamed-thread`` — a ``threading.Thread(...)`` constructed without
  ``name=``: sanitizer findings, resilience-fault JSONL events, and
  profiler trace events all attribute by thread name; an anonymous
  ``Thread-7`` in a chaos artifact is unactionable.
* ``bare-acquire`` — a statement-level ``lock.acquire()``: no ``with``
  scope means any exception between acquire and release leaks the lock
  (and the sanitizer cannot pair the sites).  Try-acquires whose result
  is consumed (``if lock.acquire(blocking=False):``) are fine.
* ``sleep-under-lock`` — ``time.sleep`` lexically inside a ``with``
  block whose context names a lock/condition: every thread queued on
  that lock waits the sleep out too.
* ``unjoined-thread-in-init`` — a class whose ``__init__`` (or
  ``start``-named method) starts a ``Thread`` but that registers no
  lifecycle method (``close``/``stop``/``shutdown``/``kill``/
  ``join``/``reset``/``__exit__``/``__del__``): nothing can ever join
  the worker, so it leaks by construction.

Observability lint (the telemetry-plane registration contract):

* ``untracked-stats`` — a class defining a public ``stats()`` method in
  a file that never calls ``obs.metrics.register_producer``: the stats
  dict exists but the scrape plane (the ``metrics`` transport frame,
  `FleetManager.scrape`, ``tools/mxtop.py``) cannot see it — a
  subsystem invents a private observability shape instead of joining
  the registry.  Register the producer under a stable dotted
  namespace, or suppress inline for protocol stubs / remote fetches
  whose numbers are registered elsewhere.

Sparse-embedding lint (the mxembed wire contract):

* ``dense-grad-for-embedding`` — a training loop calling ``kv.push``
  with the full dense gradient of an embedding-named parameter: one
  batch touches a handful of rows, but the push ships — and the
  server's updater applies — the whole ``(rows, dim)`` table every
  step.  Push row_sparse (``grad.tostype('row_sparse')``) or host the
  table on a `embedding.ShardedEmbedding`, whose ``push_grad`` moves
  only the touched rows to their owning shards.

Suppression: append ``# mxlint: disable`` (everything on the line) or
``# mxlint: disable=<code>[,<code>...]`` to the offending line.
"""
from __future__ import annotations

import ast
import re

from .findings import Finding, Report, WARN

__all__ = ["scan_source", "scan_file", "CONCURRENCY_CODES"]

# the static half of the mxtsan tier: `mxlint --tsan-report` restricts
# its package sweep to exactly these codes
CONCURRENCY_CODES = frozenset({"unnamed-thread", "bare-acquire",
                               "sleep-under-lock",
                               "unjoined-thread-in-init"})

_SYNC_METHODS = {"asnumpy", "asscalar", "item", "wait_to_read"}
_SYNC_FREE = {"waitall"}
_KV_KEYWORDS = {"kvstore", "kv_store"}
_KV_SINKS = {"fit", "init_optimizer", "Trainer", "create"}
_RETRY_CALLS = {"connect", "create_connection", "request", "recv_msg",
                "send_msg", "urlopen"}
# the host-level cross-host collective verbs (parallel.collectives API +
# the kvstore collective plane's methods); a lost host hangs any of them
# forever when dispatched outside a watchdog scope
_COLLECTIVE_CALLS = {"all_reduce", "all_gather", "reduce_scatter",
                     "ppermute", "psum_scatter", "allreduce",
                     "allreduce_many"}
# decorators marking device code, where collectives are XLA-scheduled
_DEVICE_DECORATORS = {"jit", "pjit", "pmap", "shard_map", "custom_vjp"}
# identifiers that mark a with-scope (or wrapper call) as supervised.
# Token-wise on word boundaries (snake_case AND camelCase): "supervised",
# "JobSupervisor", "watchdog" qualify; "unsupervised"/"run_unsupervised"
# must NOT — a name that says it is not supervised cannot silence the lint
_NAME_TOKEN_RE = re.compile(r"[A-Za-z][a-z]*")


def _supervised_name(ident):
    return any(tok.lower().startswith(("supervis", "watchdog"))
               for tok in _NAME_TOKEN_RE.findall(ident))
_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable(?:=([\w\-, ]+))?")

_PASS_BY_CODE = {"host-sync-in-loop": "source.hostsync",
                 "host-transfer-in-graph": "source.hostsync",
                 "kvstore-local-on-tpu": "source.kvstore",
                 "unbucketed-push": "source.kvstore",
                 "unbounded-retry": "source.retry",
                 "bare-except": "source.except",
                 "nan-swallow": "source.guardian",
                 "unsupervised-collective": "source.supervisor",
                 "router-bypass": "source.router",
                 "unguarded-model-swap": "source.loop",
                 "fixed-fleet": "source.fleet",
                 "unnamed-thread": "source.thread",
                 "bare-acquire": "source.locks",
                 "sleep-under-lock": "source.locks",
                 "unjoined-thread-in-init": "source.thread",
                 "untracked-stats": "source.obs",
                 "dense-grad-for-embedding": "source.embedding",
                 "blocking-h2d-in-loop": "source.io",
                 "kv-cache-recompile": "source.decode",
                 "unsharded-device-put": "source.sharding"}

# calls that mark a script as mesh-configured (SPMD placement is in
# play, so bare device placement deserves a look)
_MESH_CALLS = {"make_mesh", "mesh_from_spec", "local_mesh", "Mesh",
               "rebuild"}

# identifiers that mark a concatenation target as a decode KV cache
# (token substrings of the assignment target)
_CACHEY = ("cache", "kv", "past_key", "past_kv")
_CONCAT_CALLS = {"concatenate", "concat", "hstack", "vstack", "stack"}

# identifiers that mark a with-scope as a critical section for the
# sleep-under-lock lint (token substrings of the context expression)
_LOCKISH = ("lock", "mutex", "cond", "idle")
# lifecycle methods that make a thread-starting class joinable
_LIFECYCLE_METHODS = {"close", "stop", "shutdown", "kill", "join",
                      "reset", "__exit__", "__del__"}


def _suppressed(lines, lineno, code):
    if 1 <= lineno <= len(lines):
        m = _DISABLE_RE.search(lines[lineno - 1])
        if m:
            codes = m.group(1)
            if codes is None:
                return True
            return code in {c.strip() for c in codes.split(",")}
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, filename, lines):
        self.filename = filename
        self.lines = lines
        self.loop_depth = 0
        self.loop_targets = []   # per enclosing loop: its target names
        self.findings = []
        self.uses_tpu = False
        self.kv_local_sites = []   # (lineno, sink name)
        self.router_configured = False
        self.served_names = set()    # names bound from ServedModel(...)
        self.bypass_sites = []       # (lineno, what) — emitted only when
                                     # a router is configured
        self.fleet_configured = False
        self.fixed_router_sites = []  # (lineno, what) — emitted only
                                      # when a fleet/autoscaler is too
        self.loop_configured = False  # script constructs a LoopController
        self.swap_sites = []          # (lineno, what) — direct swap
                                      # calls, emitted only when a
                                      # LoopController is configured
        self.supervised_depth = 0  # inside a supervisor/watchdog `with`
        self.device_depth = 0      # inside a jit/pjit/shard_map function
        self.lock_with_depth = 0   # inside a `with <lock-ish>:` block
        self.stats_defs = []       # (lineno, class name) of `def stats`
        self.registers_producer = False   # file calls register_producer
        self._h2d_seen = set()     # node ids already flagged (nested loops)
        self.mesh_configured = False      # file builds/passes a mesh
        self.unsharded_put_sites = []     # (lineno, call name) — emitted
                                          # only when a mesh is configured

    # -- loops ---------------------------------------------------------------
    def _check_blocking_h2d(self, node):
        """A TRAINING loop (its body runs a training update) that also
        feeds arrays to the device directly: every `device_put` /
        `.as_in_context()` there blocks the loop on the transfer it
        could have overlapped — the h2d staging ring's job."""
        if self._train_update_call(node) is None:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or id(sub) in self._h2d_seen:
                continue
            func = sub.func
            name = func.attr if isinstance(func, ast.Attribute) else \
                func.id if isinstance(func, ast.Name) else None
            if name in ("device_put", "as_in_context"):
                self._h2d_seen.add(id(sub))
                self._add(
                    "blocking-h2d-in-loop", sub.lineno,
                    f"{name}() inside a training loop blocks the step on "
                    "its own input transfer; the h2d staging ring "
                    "(MXNET_IO_RING / io_plane.DevicePrefetchIter) "
                    "decodes, stages and transfers batch k+1 on the "
                    "mx-io-h2d thread while batch k computes — feed the "
                    "loop from the ring (Module.fit wraps its iterator "
                    "automatically)")

    def _loop(self, node):
        self._check_blocking_h2d(node)
        targets = set()
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    targets.add(sub.id)
        self.loop_depth += 1
        self.loop_targets.append(targets)
        self.generic_visit(node)
        self.loop_targets.pop()
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = _loop

    def visit_While(self, node):
        test = node.test
        if isinstance(test, ast.Constant) and test.value in (True, 1):
            self._check_unbounded_retry(node)
        self._loop(node)

    def _check_unbounded_retry(self, node):
        """``while True`` around a TRIED connect/request (a try/except
        that swallows the failure and loops again) with neither a
        deadline reference nor a `raise`: nothing ever bounds the loop.
        A bare call without a try is not a retry loop — a dead peer's
        exception escapes it, which IS a bound."""
        retry_line = None
        bounded = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Try):
                for handler in sub.handlers:
                    # break/return IN THE HANDLER exits the loop on
                    # failure — that is a bound (a read loop's
                    # `except: break`); break in the TRY body is the
                    # success path and bounds nothing
                    for inner in ast.walk(handler):
                        if isinstance(inner, (ast.Break, ast.Return)):
                            bounded = True
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call):
                        func = inner.func
                        name = func.attr \
                            if isinstance(func, ast.Attribute) else \
                            func.id if isinstance(func, ast.Name) else None
                        if name in _RETRY_CALLS and retry_line is None:
                            retry_line = inner.lineno
            elif isinstance(sub, ast.Raise):
                bounded = True
            else:
                ident = sub.id if isinstance(sub, ast.Name) else \
                    sub.attr if isinstance(sub, ast.Attribute) else ""
                if "deadline" in ident.lower():
                    bounded = True
        if retry_line is not None and not bounded:
            self._add("unbounded-retry", node.lineno,
                      "'while True' retry loop around a connect/request "
                      f"call (line {retry_line}) with no deadline and no "
                      "raise: a permanently dead peer spins this loop "
                      "forever — bound it with a monotonic deadline or "
                      "resilience.RetryPolicy")

    # -- exception handling --------------------------------------------------
    def _train_update_call(self, node):
        """Line of the first training-update call lexically inside
        `node` — Module.fit / fit_step / forward_backward, or .step()
        on a receiver whose name mentions a trainer — else None."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) or \
                    not isinstance(sub.func, ast.Attribute):
                continue
            attr = sub.func.attr
            if attr in ("fit", "fit_step", "forward_backward"):
                return sub.lineno
            if attr == "step":
                recv = sub.func.value
                ident = recv.id if isinstance(recv, ast.Name) else \
                    recv.attr if isinstance(recv, ast.Attribute) else ""
                if "trainer" in ident.lower():
                    return sub.lineno
        return None

    def _check_nan_swallow(self, node):
        """try around a training update whose handler swallows and keeps
        going (continue/pass, no raise) — hand-rolled NaN tolerance."""
        update_line = None
        for stmt in node.body:
            update_line = self._train_update_call(stmt)
            if update_line is not None:
                break
        if update_line is None:
            return
        for handler in node.handlers:
            if any(isinstance(s, ast.Raise) for s in ast.walk(handler)):
                continue
            swallows = any(isinstance(s, ast.Continue)
                           for s in ast.walk(handler)) or \
                all(isinstance(s, ast.Pass) for s in handler.body)
            mentions_nan = any(
                isinstance(s, ast.Call) and (
                    (isinstance(s.func, ast.Attribute) and
                     s.func.attr in ("isnan", "isfinite")) or
                    (isinstance(s.func, ast.Name) and
                     s.func.id in ("isnan", "isfinite")))
                for s in ast.walk(handler))
            if swallows or mentions_nan:
                self._add(
                    "nan-swallow", handler.lineno,
                    "exception swallowed around a training update (line "
                    f"{update_line}) with the loop continuing: "
                    "hand-rolled NaN/failure tolerance silently loses "
                    "steps, desynchronizes multi-worker runs, and leaves "
                    "no quarantine trail — use the training guardian "
                    "(MXNET_GUARDIAN: in-graph skip-batch, loss-spike "
                    "rollback, quarantine) instead")
                return

    def visit_Try(self, node):
        self._check_nan_swallow(node)
        for handler in node.handlers:
            bare = handler.type is None
            broad = isinstance(handler.type, ast.Name) and \
                handler.type.id in ("Exception", "BaseException")
            if not bare and not broad:
                continue
            has_raise = any(isinstance(s, ast.Raise)
                            for s in ast.walk(handler))
            swallow_only = all(isinstance(s, (ast.Pass, ast.Continue))
                               for s in handler.body)
            if (bare and not has_raise) or (broad and swallow_only):
                what = "bare 'except:'" if bare else \
                    f"'except {handler.type.id}:' that only swallows"
            else:
                continue
            self._add("bare-except", handler.lineno,
                      f"{what} hides MXNetError — including structured "
                      "failover signals (ServerLostError) — so the script "
                      "keeps 'running' on a dead cluster; catch specific "
                      "exceptions or re-raise")
        self.generic_visit(node)

    # functions defined INSIDE a loop body don't run per-iteration at the
    # definition site; reset the loop context for their bodies
    def _fresh_scope(self, node):
        saved, self.loop_depth = self.loop_depth, 0
        saved_targets, self.loop_targets = self.loop_targets, []
        device = any(
            _DEVICE_DECORATORS & self._idents(d)
            for d in getattr(node, "decorator_list", ()))
        if device:
            self.device_depth += 1
        self.generic_visit(node)
        if device:
            self.device_depth -= 1
        self.loop_depth = saved
        self.loop_targets = saved_targets

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _fresh_scope

    @classmethod
    def _constant_expr(cls, node):
        """Literal (or container/unary-minus of literals): a value that
        exists at trace time, not per step."""
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, (ast.List, ast.Tuple)):
            return all(cls._constant_expr(e) for e in node.elts)
        if isinstance(node, ast.UnaryOp):
            return cls._constant_expr(node.operand)
        # dtype mentions (np.float32 etc.) are constants too
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("np", "numpy", "onp", "jnp"):
            return True
        return False

    @staticmethod
    def _idents(node):
        """Every Name/Attribute identifier inside `node` (decorator or
        with-item expressions — 'does this expression mention X?')."""
        out = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                out.add(sub.attr)
        return out

    # -- assignments (ServedModel bindings for the router-bypass lint) -------
    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call) and \
                "ServedModel" in self._idents(node.value.func):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.served_names.add(tgt.id)
        self._check_kv_cache_growth(node)
        self.generic_visit(node)

    def _check_kv_cache_growth(self, node):
        """``cache = concatenate([cache, new], ...)`` inside a decode
        loop: the cache's length axis grows every token, so every step
        presents XLA a NOVEL shape — one multi-second compile per token
        generated.  The fix is a fixed-shape preallocated cache written
        with dynamic_update_slice (a donated carry, the
        `serving.DecodeEngine` / `llm.decode_core` discipline) so the
        step program's signature never changes."""
        if not self.loop_depth or not isinstance(node.value, ast.Call):
            return
        func = node.value.func
        name = func.attr if isinstance(func, ast.Attribute) else \
            func.id if isinstance(func, ast.Name) else None
        if name not in _CONCAT_CALLS:
            return
        targets = [t.id for tgt in node.targets for t in ast.walk(tgt)
                   if isinstance(t, ast.Name)]
        fed_back = {s.id for s in ast.walk(node.value)
                    if isinstance(s, ast.Name)}
        for tgt in targets:
            if tgt in fed_back and \
                    any(tok in tgt.lower() for tok in _CACHEY):
                self._add(
                    "kv-cache-recompile", node.lineno,
                    f"KV cache '{tgt}' grows by {name}() every loop "
                    "iteration: each decode step presents XLA a new "
                    "shape, costing one compile per generated token — "
                    "preallocate a fixed-shape cache and write with "
                    "dynamic_update_slice (the serving.DecodeEngine "
                    "donated-carry discipline), padding prompts onto a "
                    "bucket ladder")
                return

    # -- supervised scopes ---------------------------------------------------
    def _visit_with(self, node):
        supervised = any(
            any(_supervised_name(ident) for ident in
                self._idents(item.context_expr))
            for item in node.items)
        lockish = any(
            any(tok in ident.lower() for tok in _LOCKISH)
            for item in node.items
            for ident in self._idents(item.context_expr))
        if supervised:
            self.supervised_depth += 1
        if lockish:
            self.lock_with_depth += 1
        self.generic_visit(node)
        if supervised:
            self.supervised_depth -= 1
        if lockish:
            self.lock_with_depth -= 1

    visit_With = visit_AsyncWith = _visit_with

    # -- classes (thread-lifecycle + untracked-stats lints) ------------------
    def visit_ClassDef(self, node):
        for fn in node.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "stats":
                # deferred: emitted only if the whole FILE never
                # registers a producer (scan_source post-pass)
                self.stats_defs.append((fn.lineno, node.name))
        methods = {n.name for n in node.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not (methods & _LIFECYCLE_METHODS):
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name != "__init__" and "start" not in fn.name:
                    continue
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and \
                            "Thread" in self._idents(sub.func):
                        self._add(
                            "unjoined-thread-in-init", sub.lineno,
                            f"class '{node.name}' starts a Thread in "
                            f"{fn.name}() but registers no lifecycle "
                            "method (close/stop/shutdown/join): nothing "
                            "can ever join this worker, so it leaks by "
                            "construction — add a close() that joins "
                            "with a timeout (tsan.join_thread)")
        self.generic_visit(node)

    # -- statements (bare-acquire lint) --------------------------------------
    def visit_Expr(self, node):
        call = node.value
        if isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr == "acquire":
            self._add("bare-acquire", node.lineno,
                      "statement-level .acquire() without a 'with' "
                      "scope: any exception before the matching "
                      "release() leaks the lock and deadlocks the next "
                      "acquirer — use 'with lock:' (or consume the "
                      "try-acquire's result)")
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------------
    def _add(self, code, lineno, message):
        if _suppressed(self.lines, lineno, code):
            return
        self.findings.append(Finding(
            _PASS_BY_CODE.get(code, "source"), code, WARN, message,
            location=f"{self.filename}:{lineno}"))

    def visit_Call(self, node):
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name == "tpu":
            self.uses_tpu = True
        if name == "register_producer":
            self.registers_producer = True
        # -- sharding-aware placement (mxshard's AST half) -------------------
        if name in _MESH_CALLS or \
                any(kw.arg == "mesh" and
                    not (isinstance(kw.value, ast.Constant) and
                         kw.value.value is None)
                    for kw in node.keywords):
            self.mesh_configured = True
        if name == "device_put":
            sharded = len(node.args) >= 2 or \
                any(kw.arg in ("sharding", "device", "devices", "dst")
                    for kw in node.keywords)
            if not sharded:
                self.unsharded_put_sites.append((node.lineno,
                                                 "device_put"))
        elif name == "as_in_context":
            self.unsharded_put_sites.append((node.lineno,
                                             "as_in_context"))
        if self.loop_depth > 0 and isinstance(func, ast.Attribute) and \
                name in _SYNC_METHODS:
            self._add("host-sync-in-loop", node.lineno,
                      f".{name}() inside a loop blocks the host on the "
                      "device every iteration; hoist it out of the loop "
                      "or batch the reads")
        if self.loop_depth > 0 and name in _SYNC_FREE:
            self._add("host-sync-in-loop", node.lineno,
                      f"{name}() inside a loop drains ALL in-flight work "
                      "every iteration")
        # -- host coercion inside a traced (jit/pjit/shard_map) function -----
        if self.device_depth > 0:
            what = None
            if isinstance(func, ast.Attribute) and \
                    name in ("asnumpy", "asscalar", "item",
                             "device_get"):
                what = f".{name}()"
            elif isinstance(func, ast.Attribute) and \
                    name in ("asarray", "array") and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id in ("np", "numpy", "onp") and \
                    not all(self._constant_expr(a) for a in node.args):
                # np.array(<literal>) is a trace-time constant baked
                # into the program — only DYNAMIC values cross to host
                what = f"{func.value.id}.{name}()"
            if what:
                self._add(
                    "host-transfer-in-graph", node.lineno,
                    f"{what} inside a jit/shard_map-decorated function: "
                    "the traced program either fails to trace or "
                    "crosses to the host on every step (mxcost flags "
                    "the jaxpr side as hidden-host-transfer) — compute "
                    "in-graph or hoist the read out of the traced "
                    "region")
        if name in ("push", "pull") and self.loop_depth > 0 and \
                isinstance(func, ast.Attribute) and node.args:
            recv_ids = self._idents(func.value)
            loop_vars = set().union(*self.loop_targets) \
                if self.loop_targets else set()
            key_ids = self._idents(node.args[0])
            if any("kv" in ident.lower() for ident in recv_ids) and \
                    key_ids & loop_vars:
                self._add(
                    "unbucketed-push", node.lineno,
                    f"per-parameter kv.{name}() inside a training loop: "
                    "collective stores advertise prefers_batched_push — "
                    "one batched call with the FULL key list reduces in "
                    "O(buckets) overlapped collectives instead of one "
                    "per parameter; hoist the loop into kv."
                    f"{name}(names, arrays) (or stream with "
                    "begin_push/push_part/end_push)")
        # -- dense grad pushed for an embedding-shaped parameter -------------
        if name == "push" and self.loop_depth > 0 and \
                isinstance(func, ast.Attribute) and len(node.args) >= 2 and \
                any("kv" in ident.lower()
                    for ident in self._idents(func.value)):
            key = node.args[0]
            key_names = {i.lower() for i in self._idents(key)}
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                key_names.add(key.value.lower())
            if any("embed" in k for k in key_names):
                val = node.args[1]
                sparse_ok = any(
                    (isinstance(sub, ast.Constant) and
                     sub.value == "row_sparse") or
                    (isinstance(sub, ast.Name) and sub.id in
                     ("RowSparseNDArray", "row_sparse_array"))
                    for sub in ast.walk(val))
                if not sparse_ok:
                    self._add(
                        "dense-grad-for-embedding", node.lineno,
                        "a training loop pushes the FULL dense gradient "
                        "of an embedding-shaped parameter: a batch "
                        "touches a handful of rows but every push ships "
                        "(and the server updates) the whole table — "
                        "push row_sparse instead (grad.tostype("
                        "'row_sparse'), or a ShardedEmbedding table "
                        "whose push_grad moves only the touched rows)")
        # -- concurrency lints (the mxtsan static half) ----------------------
        if name == "Thread" and \
                not any(kw.arg == "name" for kw in node.keywords):
            self._add("unnamed-thread", node.lineno,
                      "threading.Thread(...) without name=: sanitizer "
                      "findings, resilience-fault JSONL events, and "
                      "profiler traces attribute by thread name — name "
                      "it 'mx-<subsystem>-<role>'")
        if name == "sleep" and self.lock_with_depth > 0:
            self._add("sleep-under-lock", node.lineno,
                      "time.sleep() inside a 'with <lock>:' block parks "
                      "every thread queued on that lock behind the "
                      "sleep — move the wait outside the critical "
                      "section (or use Condition.wait with a timeout)")
        if name in _KV_SINKS:
            for kw in node.keywords:
                if kw.arg in _KV_KEYWORDS and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value == "local":
                    self.kv_local_sites.append((node.lineno, name))
        # -- router bypass ---------------------------------------------------
        if name == "ReplicaRouter":
            self.router_configured = True
            # a hand-rolled FIXED replica population: a list/tuple
            # literal (or comprehension) as the replicas argument —
            # flagged only when the script ALSO configures the fleet
            # autoscaler, which should own membership instead
            replicas_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "replicas"), None)
            if isinstance(replicas_arg, (ast.List, ast.Tuple)) \
                    and replicas_arg.elts:
                self.fixed_router_sites.append(
                    (node.lineno, "a %d-element replica list literal"
                     % len(replicas_arg.elts)))
            elif isinstance(replicas_arg, (ast.ListComp,
                                           ast.GeneratorExp)):
                self.fixed_router_sites.append(
                    (node.lineno, "a replica comprehension"))
        elif name in ("FleetManager", "Autoscaler"):
            self.fleet_configured = True
        elif name == "ModelServer":
            self.bypass_sites.append(
                (node.lineno, "ModelServer(...) instantiated"))
        elif name == "infer":
            recv = func.value if isinstance(func, ast.Attribute) else None
            recv_name = recv.id if isinstance(recv, ast.Name) else None
            # `model.infer(...)` on a ServedModel binding, or a direct
            # `ServedModel(...).infer(...)` / `ServedModel.load(...).infer`
            if (recv_name in self.served_names
                    or (recv is not None
                        and "ServedModel" in self._idents(recv))):
                self.bypass_sites.append(
                    (node.lineno, "direct ServedModel.infer() call"))
        # -- unguarded model swap (canary-gate bypass) -----------------------
        if name == "LoopController":
            self.loop_configured = True
        elif name == "swap_weights" or name == "swap_one":
            self.swap_sites.append(
                (node.lineno, f"direct {name}() call"))
        elif name == "swap" and isinstance(func, ast.Attribute) and \
                any("replica" in i.lower()
                    for i in self._idents(func.value)):
            self.swap_sites.append(
                (node.lineno, "direct replica.swap() call"))
        if name in _COLLECTIVE_CALLS and isinstance(func, ast.Attribute) \
                and self.supervised_depth == 0 and self.device_depth == 0:
            self._add("unsupervised-collective", node.lineno,
                      f"cross-host collective .{name}() dispatched outside "
                      "a supervisor/watchdog scope: one lost host hangs it "
                      "forever with no error — wrap it with "
                      "parallel.collectives.supervised(...) or run under "
                      "a resilience.JobSupervisor")
        if name is not None and _supervised_name(name):
            # arguments of supervised(...)/watchdog wrappers ARE the
            # supervised scope (the lambda handed to the watchdog)
            self.supervised_depth += 1
            self.generic_visit(node)
            self.supervised_depth -= 1
            return
        self.generic_visit(node)


def scan_source(text, filename="<string>"):
    """Lint python source; returns a Report."""
    report = Report(target=filename)
    try:
        tree = ast.parse(text, filename=filename)
    except SyntaxError as e:
        report.add(Finding("source.parse", "syntax-error", WARN,
                           f"cannot parse: {e.msg}",
                           location=f"{filename}:{e.lineno or 0}"))
        return report
    lines = text.splitlines()
    v = _Visitor(filename, lines)
    v.visit(tree)
    report.extend(v.findings)
    if v.router_configured:
        for lineno, what in v.bypass_sites:
            if _suppressed(lines, lineno, "router-bypass"):
                continue
            report.add(Finding(
                "source.router", "router-bypass", WARN,
                f"{what} in a script that configures a ReplicaRouter: "
                "this traffic bypasses the router's failover, health "
                "checks, and priority-class shedding — route it through "
                "router.submit()/predict()",
                location=f"{filename}:{lineno}"))
    if v.loop_configured:
        for lineno, what in v.swap_sites:
            if _suppressed(lines, lineno, "unguarded-model-swap"):
                continue
            report.add(Finding(
                "source.loop", "unguarded-model-swap", WARN,
                f"{what} in a script that constructs a LoopController: "
                "pushing weights straight onto the fleet bypasses the "
                "canary gate the script itself set up — publish the "
                "checkpoint to the ModelRegistry and let "
                "LoopController.poll_once() canary-score it before the "
                "rolling swap promotes it",
                location=f"{filename}:{lineno}"))
    if v.fleet_configured:
        for lineno, what in v.fixed_router_sites:
            if _suppressed(lines, lineno, "fixed-fleet"):
                continue
            report.add(Finding(
                "source.fleet", "fixed-fleet", WARN,
                f"ReplicaRouter constructed with {what} in a script "
                "that configures the fleet autoscaler: a hand-pinned "
                "replica list caps capacity at what the script "
                "hard-coded and bypasses host-aware placement/backfill "
                "— hand the host registry to FleetManager and let "
                "placement spawn the replicas",
                location=f"{filename}:{lineno}"))
    if not v.registers_producer:
        for lineno, cls in v.stats_defs:
            if _suppressed(lines, lineno, "untracked-stats"):
                continue
            report.add(Finding(
                "source.obs", "untracked-stats", WARN,
                f"class '{cls}' defines a public stats() dict but this "
                "file never registers it with the metrics registry "
                "(obs.metrics.register_producer): the scrape plane — "
                "the 'metrics' transport frame, FleetManager.scrape, "
                "mxtop — cannot see these numbers; register the "
                "producer under a stable dotted namespace",
                location=f"{filename}:{lineno}"))
    if v.mesh_configured:
        for lineno, call in v.unsharded_put_sites:
            if _suppressed(lines, lineno, "unsharded-device-put"):
                continue
            report.add(Finding(
                "source.sharding", "unsharded-device-put", WARN,
                f"{call}() without a sharding argument in a script that "
                "configures a device mesh: the array lands replicated "
                "(or pinned to one device) instead of sharded — pass a "
                "NamedSharding (parallel.shard_params applies the rule "
                "set) so a multi-MB array costs HBM on one shard, not "
                "every device",
                location=f"{filename}:{lineno}"))
    if v.uses_tpu:
        for lineno, sink in v.kv_local_sites:
            if _suppressed(lines, lineno, "kvstore-local-on-tpu"):
                continue
            report.add(Finding(
                "source.kvstore", "kvstore-local-on-tpu", WARN,
                f"kvstore='local' passed to {sink}() in a script that "
                "creates TPU contexts: 'local' reduces gradients through "
                "host memory; use kvstore='device' (ICI collectives)",
                location=f"{filename}:{lineno}"))
    return report


def scan_file(path):
    with open(path, encoding="utf-8", errors="replace") as f:
        return scan_source(f.read(), filename=str(path))
