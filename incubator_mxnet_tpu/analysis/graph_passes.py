"""Static graph passes over `Symbol` (and saved symbol JSON).

Topo-ordered analyses in the TVM/grappler pass mold: each pass walks the
graph once and returns findings, no mutation.  The catalog:

* ``graph.names``  — duplicate node names (distinct nodes sharing a name
  silently shadow each other in `tojson` / `arg_dict`), empty names.
* ``graph.dead``   — outputs of multi-output ops that no node consumes
  and no head exposes: computed, shipped through XLA, thrown away.
* ``graph.aux``    — aux-state hazards: one running-stat variable feeding
  the aux slots of several ops (racing writers), or an aux variable also
  consumed as a regular input.
* ``graph.dtype``  — float64 introduction: explicit f64 variables/casts
  (TPUs have no f64 ALU; XLA emulates slowly or demotes), plus which
  graph outputs the promotion reaches when shapes allow inference.
* ``graph.unbound``— variables whose shape can be inferred neither from
  the provided input shapes nor from op attrs (bind will fail there).
* ``graph.layout`` — TPU tiling hints: channel/feature dims that are not
  multiples of 8 (sublane) / 128 (lane) pad to the next tile and waste
  MXU throughput.  Hint severity: advisory, not a defect.

Per-node suppression: set the ``__lint__`` attr on a Variable/op to
``"off"`` (suppress everything on that node) or a comma list of codes,
e.g. ``attr={"__lint__": "tpu-layout,dead-output"}``.
"""
from __future__ import annotations

import json as _json

import numpy as _np

from ..base import np_dtype
from .findings import Finding, Report, ERROR, WARN, HINT

__all__ = ["check", "check_json", "PASS_CATALOG"]

PASS_CATALOG = {
    "graph.names": ("duplicate-name", "empty-name", "bad-json",
                    "unloadable"),
    "graph.dead": ("dead-output", "unreachable-node"),
    "graph.aux": ("shared-aux", "aux-as-input", "unreachable-node"),
    "graph.dtype": ("f64-promotion", "f64-output"),
    "graph.unbound": ("unbound-input",),
    "graph.layout": ("tpu-layout",),
}

# feature/channel attrs per op for the layout pass
_FEATURE_ATTRS = {
    "FullyConnected": ("num_hidden", "num_hidden"),
    "Convolution": ("num_filter", "num_filter"),
    "Deconvolution": ("num_filter", "num_filter"),
    "Embedding": ("output_dim", "output_dim"),
    "RNN": ("state_size", "state_size"),
}

# multi-output ops whose trailing outputs are optional state taps the
# caller may legitimately ignore: op name -> index of the first optional
# output (int, or a callable over the node attrs)
_OPTIONAL_TAIL_OUTPUTS = {
    "RNN": 1,
    # control-flow ops: outputs past num_out_data are the final loop
    # states (an unrolled LSTM discards them by design)
    "_foreach": lambda attrs: int(attrs.get("num_out_data", 0)),
    "_while_loop": lambda attrs: int(attrs.get("num_out_data", 0)),
}


def _suppressed(node, code):
    tag = node._extra_attrs.get("__lint__")
    if not tag:
        return False
    tag = str(tag)
    return tag == "off" or code in {t.strip() for t in tag.split(",")}


def _finding(out, node, pass_name, code, severity, message):
    if not _suppressed(node, code):
        out.append(Finding(pass_name, code, severity, message,
                           node=node.name))


# ---------------------------------------------------------------------------
# individual passes
# ---------------------------------------------------------------------------

def _pass_names(symbol, topo):
    out = []
    seen = {}
    for node in topo:
        if not str(node.name).strip():
            _finding(out, node, "graph.names", "empty-name", ERROR,
                     "node has an empty name; it cannot be addressed in "
                     "arg_dict / saved JSON")
            continue
        first = seen.get(node.name)
        if first is None:
            seen[node.name] = node
            continue
        involves_var = node.is_variable or first.is_variable
        _finding(out, node, "graph.names", "duplicate-name",
                 ERROR if involves_var else WARN,
                 f"two distinct nodes share the name '{node.name}'; "
                 + ("arg_dict collapses the duplicates and bind "
                    "trains/feeds the wrong arrays (bind rejects this)"
                    if involves_var else
                    "by-name output lookup and tojson round-trips "
                    "silently shadow one of them"))
    return out


def _pass_dead_outputs(symbol, topo):
    consumed = set()
    for node in topo:
        for src, idx in node.inputs:
            consumed.add((id(src), idx))
    heads = {(id(n), i) for n, i in symbol._entries}
    out = []
    for node in topo:
        if node.is_variable:
            continue
        nout = node.num_outputs()
        if nout <= 1:
            continue  # single-output non-heads cannot appear in topo
        optional_from = _OPTIONAL_TAIL_OUTPUTS.get(node.op.name, nout)
        if callable(optional_from):
            optional_from = optional_from(node.attrs)
        for i in range(nout):
            if i >= optional_from:
                continue
            if (id(node), i) not in consumed and (id(node), i) not in heads:
                _finding(out, node, "graph.dead", "dead-output", WARN,
                         f"output {i} of '{node.name}' "
                         f"('{node.name}_output{i}') is computed but never "
                         "consumed and is not a graph head — dead compute "
                         "shipped through XLA")
    return out


def _pass_aux(symbol, topo):
    out = []
    aux_writers = {}   # id(var) -> (var, [op names])
    aux_readers = {}   # id(var) -> [op names] via NON-aux slots
    for node in topo:
        if node.is_variable:
            continue
        naux = node.op.num_aux(node.attrs)
        n_in = len(node.inputs)
        for k, (src, _idx) in enumerate(node.inputs):
            if not src.is_variable:
                continue
            if naux and k >= n_in - naux:
                aux_writers.setdefault(id(src), (src, []))[1].append(
                    node.name)
            else:
                aux_readers.setdefault(id(src), []).append(node.name)
    for vid, (var, writers) in aux_writers.items():
        if len(writers) > 1:
            _finding(out, var, "graph.aux", "shared-aux", WARN,
                     f"aux state '{var.name}' feeds the running-state "
                     f"slots of {len(writers)} ops ({', '.join(writers[:4])}"
                     f"{', ...' if len(writers) > 4 else ''}); every train "
                     "step races their writes — last writer wins")
        readers = aux_readers.get(vid)
        if readers:
            _finding(out, var, "graph.aux", "aux-as-input", WARN,
                     f"aux state '{var.name}' is also consumed as a "
                     f"regular input by {readers[0]}; it will be updated "
                     "in place under that reader")
    return out


def _is_f64(value):
    try:
        return np_dtype(value) == _np.float64
    except Exception:
        return False


def _pass_dtype(symbol, topo, env):
    out = []
    origins = []
    for node in topo:
        if node.is_variable:
            if _is_f64(node._extra_attrs.get("__dtype__")):
                origins.append(node)
                _finding(out, node, "graph.dtype", "f64-promotion", WARN,
                         f"variable '{node.name}' is declared float64; "
                         "TPUs have no f64 ALU — XLA emulates it slowly "
                         "or demotes with precision surprises")
            continue
        for key, val in node.attrs.items():
            if key in ("dtype", "out_type") and _is_f64(val):
                origins.append(node)
                _finding(out, node, "graph.dtype", "f64-promotion", WARN,
                         f"op '{node.name}' ({node.op.name}) produces "
                         f"float64 ({key}={val!r}); TPUs have no f64 ALU "
                         "— the whole downstream graph pays for emulation")
    if origins and env:
        f64_heads = []
        outs = symbol.list_outputs()
        for oname, (node, idx) in zip(outs, symbol._entries):
            avals = env.get(id(node))
            if avals and idx < len(avals) and avals[idx] is not None and \
                    _np.dtype(avals[idx].dtype) == _np.float64:
                f64_heads.append(oname)
        if f64_heads:
            n, _i = symbol._entries[0]
            out.append(Finding(
                "graph.dtype", "f64-output", WARN,
                "the f64 promotion reaches graph output(s) "
                f"{', '.join(f64_heads[:4])}"
                f"{', ...' if len(f64_heads) > 4 else ''}; every consumer "
                "inherits the emulation cost", node=n.name))
    return out


def _pass_unbound(symbol, topo, shapes):
    """Variables the framework's own partial shape inference cannot solve
    from the provided inputs — `simple_bind` will fail exactly there."""
    try:
        kw = {k: tuple(v) for k, v in shapes.items() if v}
        arg_shapes, _, aux_shapes = symbol.infer_shape_partial(**kw)
    except Exception:
        return []   # inference itself broke; other passes still apply
    out = []
    names = symbol.list_arguments() + symbol.list_auxiliary_states()
    solved = list(arg_shapes or []) + list(aux_shapes or [])
    var_nodes = {n.name: n for n in topo if n.is_variable}
    for name, shp in zip(names, solved):
        if shp is not None and all(shp):
            continue
        node = var_nodes.get(name)
        if node is not None:
            _finding(out, node, "graph.unbound", "unbound-input", WARN,
                     f"shape of variable '{name}' cannot be inferred "
                     "from the provided input shapes or op attrs; "
                     "simple_bind will fail here — provide its shape")
    return out


def _pass_layout(symbol, topo):
    out = []
    for node in topo:
        if node.is_variable or node.op.name not in _FEATURE_ATTRS:
            continue
        attr, label = _FEATURE_ATTRS[node.op.name]
        try:
            d = int(node.attrs.get(attr))
        except (TypeError, ValueError):
            continue
        if d <= 0 or (d % 8 == 0 and d % 128 == 0):
            continue
        lane_pad = -d % 128
        sub_pad = -d % 8
        waste = 100.0 * lane_pad / (d + lane_pad)
        parts = []
        if sub_pad:
            parts.append(f"pads {sub_pad} sublanes to the next multiple "
                         "of 8")
        if lane_pad:
            parts.append(f"pads {lane_pad} lanes to the next multiple of "
                         f"128 ({waste:.0f}% of the padded tile wasted)")
        _finding(out, node, "graph.layout", "tpu-layout", HINT,
                 f"'{node.name}' {label}={d} is not TPU-tile aligned: "
                 + "; ".join(parts))
    return out


# ---------------------------------------------------------------------------
# best-effort abstract evaluation (shape+dtype), partial-tolerant
# ---------------------------------------------------------------------------

def _abstract_env(symbol, shapes, dtypes=None):
    """{id(node): tuple(ShapeDtypeStruct|None)} walking topo order; a node
    whose inputs cannot be resolved gets None (partial inference — the
    passes that consume the env skip unknowns).  Variables seed from the
    provided `shapes`, then ``__shape__`` attrs; declared ``__dtype__``
    attrs carry real dtypes so f64 propagation is visible, and the
    optional `dtypes` map ({var_name: dtype}) overrides both — a
    quantized model's int8 weights live in its params dict, not its
    variable attrs, and the cost analyzer feeds them through here."""
    import jax
    from ..symbol.symbol import _solve_param_shapes

    shapes = dict(shapes or {})
    dtypes = dict(dtypes or {})
    topo = symbol._topo()
    env = {}

    def var_aval(node):
        cand = None
        if node.name in shapes and shapes[node.name]:
            cand = shapes[node.name]
        elif "__shape__" in node._extra_attrs:
            cand = node._extra_attrs["__shape__"]
        if isinstance(cand, str):
            # saved JSON stringifies attrs: "(4, 8)" -> (4, 8)
            import ast as _ast
            try:
                cand = _ast.literal_eval(cand)
            except (ValueError, SyntaxError):
                cand = None
        cand = tuple(cand) if cand is not None else None
        if cand is None or not all(isinstance(d, int) and d > 0
                                   for d in cand):
            return None
        dt = _np.float32
        declared = dtypes.get(node.name,
                              node._extra_attrs.get("__dtype__"))
        if declared is not None:
            try:
                dt = np_dtype(declared)
            except Exception:
                pass
        return jax.ShapeDtypeStruct(cand, dt)

    for node in topo:
        if node.is_variable:
            aval = var_aval(node)
            env[id(node)] = (aval,) if aval is not None else None
            continue
        ins = []
        unknown = False
        for src, idx in node.inputs:
            e = env.get(id(src))
            if e is None or idx >= len(e) or e[idx] is None:
                unknown = True
                break
            ins.append(e[idx])
        if unknown:
            try:
                solved = _solve_param_shapes(node, env)
            except Exception:
                solved = False
            if solved:
                ins = [env[id(src)][idx] for src, idx in node.inputs]
            else:
                env[id(node)] = None
                continue
        params = dict(node.attrs)
        if node.op.mode_dependent:
            params["_train"] = False
        if node.op.dynamic_params:
            for pname in node.op.dynamic_params:
                ins.append(jax.ShapeDtypeStruct((), _np.float32))
                params.pop(pname, None)
        if node.op.needs_rng:
            ins.append(jax.ShapeDtypeStruct((2,), _np.uint32))
        try:
            outv = jax.eval_shape(lambda *xs: node.op.fn(params, *xs), *ins)
        except Exception:
            env[id(node)] = None
            continue
        if not isinstance(outv, (tuple, list)):
            outv = (outv,)
        env[id(node)] = tuple(outv[:node.num_outputs()])
    return env


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check(symbol, shapes=None, hints=True, target=None):
    """Run the graph-pass catalog over a Symbol.

    Parameters
    ----------
    symbol : Symbol
    shapes : optional {var_name: shape} — enables the unbound-input pass
        and dtype propagation (same convention as `infer_shape` kwargs).
    hints : include perf hints (tpu-layout) alongside errors/warnings.
    """
    topo = symbol._topo()
    report = Report(target=target)
    report.extend(_pass_names(symbol, topo))
    report.extend(_pass_dead_outputs(symbol, topo))
    report.extend(_pass_aux(symbol, topo))
    env = {}
    try:
        env = _abstract_env(symbol, shapes)
    except Exception:
        env = {}
    report.extend(_pass_dtype(symbol, topo, env))
    if shapes:
        report.extend(_pass_unbound(symbol, topo, shapes))
    if hints:
        report.extend(_pass_layout(symbol, topo))
    return report


def _json_structural(graph, target):
    """Passes that need the raw node table: duplicate names across the
    WHOLE file and nodes unreachable from any head (a Symbol object only
    ever holds reachable nodes, so these exist only for saved JSON)."""
    out = []
    nodes = graph.get("nodes", [])
    seen = {}
    for i, jn in enumerate(nodes):
        name = jn.get("name", "")
        if not str(name).strip():
            out.append(Finding("graph.names", "empty-name", ERROR,
                               f"node #{i} has an empty name", node=str(i),
                               location=target))
            continue
        if name in seen:
            out.append(Finding(
                "graph.names", "duplicate-name", ERROR,
                f"nodes #{seen[name]} and #{i} share the name '{name}'; "
                "loading this graph silently shadows one of them",
                node=name, location=target))
        else:
            seen[name] = i
    heads = [h[0] for h in graph.get("heads", [])]
    reachable = set()
    stack = list(heads)
    while stack:
        nid = stack.pop()
        if nid in reachable or nid >= len(nodes):
            continue
        reachable.add(nid)
        for inp in nodes[nid].get("inputs", []):
            stack.append(inp[0])
    for i, jn in enumerate(nodes):
        if i in reachable:
            continue
        is_var = jn.get("op") == "null"
        kind = "aux/argument state" if is_var else "op"
        out.append(Finding(
            "graph.aux" if is_var else "graph.dead",
            "unreachable-node", WARN,
            f"{kind} '{jn.get('name')}' (node #{i}) is not reachable from "
            "any graph head — dead " +
            ("state the loader will still allocate" if is_var
             else "compute"),
            node=jn.get("name"), location=target))
    return out


def check_json(text, shapes=None, hints=True, target=None):
    """Analyze a saved symbol JSON string: structural passes over the raw
    node table, then the Symbol passes over the loadable graph."""
    report = Report(target=target)
    try:
        graph = _json.loads(text)
    except ValueError as e:
        report.add(Finding("graph.names", "bad-json", ERROR,
                           f"not valid JSON: {e}", location=target))
        return report
    if not isinstance(graph, dict) or "nodes" not in graph:
        report.add(Finding("graph.names", "bad-json", ERROR,
                           "no 'nodes' table — not a symbol JSON",
                           location=target))
        return report
    report.extend(_json_structural(graph, target))
    try:
        from ..symbol.symbol import load_json
        sym = load_json(text)
    except Exception as e:
        report.add(Finding(
            "graph.names", "unloadable", ERROR,
            f"graph does not load ({str(e)[:160]}); only structural "
            "passes ran", location=target))
        return report
    # the structural pass already covered names over the WHOLE node table
    # (the Symbol walk sees only reachable nodes) — don't double-report
    sym_report = check(sym, shapes=shapes, hints=hints, target=target)
    report.extend(f for f in sym_report.findings
                  if f.pass_name != "graph.names")
    return report
