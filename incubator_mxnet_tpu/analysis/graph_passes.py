"""Static graph passes over `Symbol` (and saved symbol JSON).

Topo-ordered analyses in the TVM/grappler pass mold: each pass walks the
graph once and returns findings, no mutation.  The catalog:

* ``graph.names``  — duplicate node names (distinct nodes sharing a name
  silently shadow each other in `tojson` / `arg_dict`), empty names.
* ``graph.dead``   — outputs of multi-output ops that no node consumes
  and no head exposes: computed, shipped through XLA, thrown away.
* ``graph.aux``    — aux-state hazards: one running-stat variable feeding
  the aux slots of several ops (racing writers), or an aux variable also
  consumed as a regular input.
* ``graph.dtype``  — float64 introduction: explicit f64 variables/casts
  (TPUs have no f64 ALU; XLA emulates slowly or demotes), plus which
  graph outputs the promotion reaches when shapes allow inference.
* ``graph.unbound``— variables whose shape can be inferred neither from
  the provided input shapes nor from op attrs (bind will fail there).
* ``graph.layout`` — TPU tiling hints: channel/feature dims that are not
  multiples of 8 (sublane) / 128 (lane) pad to the next tile and waste
  MXU throughput.  Hint severity: advisory, not a defect.

Per-node suppression: set the ``__lint__`` attr on a Variable/op to
``"off"`` (suppress everything on that node) or a comma list of codes,
e.g. ``attr={"__lint__": "tpu-layout,dead-output"}``.
"""
from __future__ import annotations

import json as _json

import numpy as _np

from ..base import np_dtype
from .findings import Finding, Report, ERROR, WARN, HINT

__all__ = ["check", "check_json", "scan_plan", "PASS_CATALOG",
           "SCAN_MIN_RUN", "SCAN_HINT_RUN"]

PASS_CATALOG = {
    "graph.names": ("duplicate-name", "empty-name", "bad-json",
                    "unloadable"),
    "graph.dead": ("dead-output", "unreachable-node"),
    "graph.aux": ("shared-aux", "aux-as-input", "unreachable-node"),
    "graph.dtype": ("f64-promotion", "f64-output"),
    "graph.unbound": ("unbound-input",),
    "graph.layout": ("tpu-layout",),
    "graph.scan": ("scan-opportunity",),
}

# feature/channel attrs per op for the layout pass
_FEATURE_ATTRS = {
    "FullyConnected": ("num_hidden", "num_hidden"),
    "Convolution": ("num_filter", "num_filter"),
    "Deconvolution": ("num_filter", "num_filter"),
    "Embedding": ("output_dim", "output_dim"),
    "RNN": ("state_size", "state_size"),
}

# multi-output ops whose trailing outputs are optional state taps the
# caller may legitimately ignore: op name -> index of the first optional
# output (int, or a callable over the node attrs)
_OPTIONAL_TAIL_OUTPUTS = {
    "RNN": 1,
    # control-flow ops: outputs past num_out_data are the final loop
    # states (an unrolled LSTM discards them by design)
    "_foreach": lambda attrs: int(attrs.get("num_out_data", 0)),
    "_while_loop": lambda attrs: int(attrs.get("num_out_data", 0)),
}


def _suppressed(node, code):
    tag = node._extra_attrs.get("__lint__")
    if not tag:
        return False
    tag = str(tag)
    return tag == "off" or code in {t.strip() for t in tag.split(",")}


def _finding(out, node, pass_name, code, severity, message):
    if not _suppressed(node, code):
        out.append(Finding(pass_name, code, severity, message,
                           node=node.name))


# ---------------------------------------------------------------------------
# individual passes
# ---------------------------------------------------------------------------

def _pass_names(symbol, topo):
    out = []
    seen = {}
    for node in topo:
        if not str(node.name).strip():
            _finding(out, node, "graph.names", "empty-name", ERROR,
                     "node has an empty name; it cannot be addressed in "
                     "arg_dict / saved JSON")
            continue
        first = seen.get(node.name)
        if first is None:
            seen[node.name] = node
            continue
        involves_var = node.is_variable or first.is_variable
        _finding(out, node, "graph.names", "duplicate-name",
                 ERROR if involves_var else WARN,
                 f"two distinct nodes share the name '{node.name}'; "
                 + ("arg_dict collapses the duplicates and bind "
                    "trains/feeds the wrong arrays (bind rejects this)"
                    if involves_var else
                    "by-name output lookup and tojson round-trips "
                    "silently shadow one of them"))
    return out


def _pass_dead_outputs(symbol, topo):
    consumed = set()
    for node in topo:
        for src, idx in node.inputs:
            consumed.add((id(src), idx))
    heads = {(id(n), i) for n, i in symbol._entries}
    out = []
    for node in topo:
        if node.is_variable:
            continue
        nout = node.num_outputs()
        if nout <= 1:
            continue  # single-output non-heads cannot appear in topo
        optional_from = _OPTIONAL_TAIL_OUTPUTS.get(node.op.name, nout)
        if callable(optional_from):
            optional_from = optional_from(node.attrs)
        for i in range(nout):
            if i >= optional_from:
                continue
            if (id(node), i) not in consumed and (id(node), i) not in heads:
                _finding(out, node, "graph.dead", "dead-output", WARN,
                         f"output {i} of '{node.name}' "
                         f"('{node.name}_output{i}') is computed but never "
                         "consumed and is not a graph head — dead compute "
                         "shipped through XLA")
    return out


def _pass_aux(symbol, topo):
    out = []
    aux_writers = {}   # id(var) -> (var, [op names])
    aux_readers = {}   # id(var) -> [op names] via NON-aux slots
    for node in topo:
        if node.is_variable:
            continue
        naux = node.op.num_aux(node.attrs)
        n_in = len(node.inputs)
        for k, (src, _idx) in enumerate(node.inputs):
            if not src.is_variable:
                continue
            if naux and k >= n_in - naux:
                aux_writers.setdefault(id(src), (src, []))[1].append(
                    node.name)
            else:
                aux_readers.setdefault(id(src), []).append(node.name)
    for vid, (var, writers) in aux_writers.items():
        if len(writers) > 1:
            _finding(out, var, "graph.aux", "shared-aux", WARN,
                     f"aux state '{var.name}' feeds the running-state "
                     f"slots of {len(writers)} ops ({', '.join(writers[:4])}"
                     f"{', ...' if len(writers) > 4 else ''}); every train "
                     "step races their writes — last writer wins")
        readers = aux_readers.get(vid)
        if readers:
            _finding(out, var, "graph.aux", "aux-as-input", WARN,
                     f"aux state '{var.name}' is also consumed as a "
                     f"regular input by {readers[0]}; it will be updated "
                     "in place under that reader")
    return out


def _is_f64(value):
    try:
        return np_dtype(value) == _np.float64
    except Exception:
        return False


def _pass_dtype(symbol, topo, env):
    out = []
    origins = []
    for node in topo:
        if node.is_variable:
            if _is_f64(node._extra_attrs.get("__dtype__")):
                origins.append(node)
                _finding(out, node, "graph.dtype", "f64-promotion", WARN,
                         f"variable '{node.name}' is declared float64; "
                         "TPUs have no f64 ALU — XLA emulates it slowly "
                         "or demotes with precision surprises")
            continue
        for key, val in node.attrs.items():
            if key in ("dtype", "out_type") and _is_f64(val):
                origins.append(node)
                _finding(out, node, "graph.dtype", "f64-promotion", WARN,
                         f"op '{node.name}' ({node.op.name}) produces "
                         f"float64 ({key}={val!r}); TPUs have no f64 ALU "
                         "— the whole downstream graph pays for emulation")
    if origins and env:
        f64_heads = []
        outs = symbol.list_outputs()
        for oname, (node, idx) in zip(outs, symbol._entries):
            avals = env.get(id(node))
            if avals and idx < len(avals) and avals[idx] is not None and \
                    _np.dtype(avals[idx].dtype) == _np.float64:
                f64_heads.append(oname)
        if f64_heads:
            n, _i = symbol._entries[0]
            out.append(Finding(
                "graph.dtype", "f64-output", WARN,
                "the f64 promotion reaches graph output(s) "
                f"{', '.join(f64_heads[:4])}"
                f"{', ...' if len(f64_heads) > 4 else ''}; every consumer "
                "inherits the emulation cost", node=n.name))
    return out


def _pass_unbound(symbol, topo, shapes):
    """Variables the framework's own partial shape inference cannot solve
    from the provided inputs — `simple_bind` will fail exactly there."""
    try:
        kw = {k: tuple(v) for k, v in shapes.items() if v}
        arg_shapes, _, aux_shapes = symbol.infer_shape_partial(**kw)
    except Exception:
        return []   # inference itself broke; other passes still apply
    out = []
    names = symbol.list_arguments() + symbol.list_auxiliary_states()
    solved = list(arg_shapes or []) + list(aux_shapes or [])
    var_nodes = {n.name: n for n in topo if n.is_variable}
    for name, shp in zip(names, solved):
        if shp is not None and all(shp):
            continue
        node = var_nodes.get(name)
        if node is not None:
            _finding(out, node, "graph.unbound", "unbound-input", WARN,
                     f"shape of variable '{name}' cannot be inferred "
                     "from the provided input shapes or op attrs; "
                     "simple_bind will fail here — provide its shape")
    return out


def _pass_layout(symbol, topo):
    out = []
    for node in topo:
        if node.is_variable or node.op.name not in _FEATURE_ATTRS:
            continue
        attr, label = _FEATURE_ATTRS[node.op.name]
        try:
            d = int(node.attrs.get(attr))
        except (TypeError, ValueError):
            continue
        if d <= 0 or (d % 8 == 0 and d % 128 == 0):
            continue
        lane_pad = -d % 128
        sub_pad = -d % 8
        waste = 100.0 * lane_pad / (d + lane_pad)
        parts = []
        if sub_pad:
            parts.append(f"pads {sub_pad} sublanes to the next multiple "
                         "of 8")
        if lane_pad:
            parts.append(f"pads {lane_pad} lanes to the next multiple of "
                         f"128 ({waste:.0f}% of the padded tile wasted)")
        _finding(out, node, "graph.layout", "tpu-layout", HINT,
                 f"'{node.name}' {label}={d} is not TPU-tile aligned: "
                 + "; ".join(parts))
    return out


# ---------------------------------------------------------------------------
# best-effort abstract evaluation (shape+dtype), partial-tolerant
# ---------------------------------------------------------------------------

def _abstract_env(symbol, shapes, dtypes=None):
    """{id(node): tuple(ShapeDtypeStruct|None)} walking topo order; a node
    whose inputs cannot be resolved gets None (partial inference — the
    passes that consume the env skip unknowns).  Variables seed from the
    provided `shapes`, then ``__shape__`` attrs; declared ``__dtype__``
    attrs carry real dtypes so f64 propagation is visible, and the
    optional `dtypes` map ({var_name: dtype}) overrides both — a
    quantized model's int8 weights live in its params dict, not its
    variable attrs, and the cost analyzer feeds them through here."""
    import jax
    from ..symbol.symbol import _solve_param_shapes

    shapes = dict(shapes or {})
    dtypes = dict(dtypes or {})
    topo = symbol._topo()
    env = {}

    def var_aval(node):
        cand = None
        if node.name in shapes and shapes[node.name]:
            cand = shapes[node.name]
        elif "__shape__" in node._extra_attrs:
            cand = node._extra_attrs["__shape__"]
        if isinstance(cand, str):
            # saved JSON stringifies attrs: "(4, 8)" -> (4, 8)
            import ast as _ast
            try:
                cand = _ast.literal_eval(cand)
            except (ValueError, SyntaxError):
                cand = None
        cand = tuple(cand) if cand is not None else None
        if cand is None or not all(isinstance(d, int) and d > 0
                                   for d in cand):
            return None
        dt = _np.float32
        declared = dtypes.get(node.name,
                              node._extra_attrs.get("__dtype__"))
        if declared is not None:
            try:
                dt = np_dtype(declared)
            except Exception:
                pass
        return jax.ShapeDtypeStruct(cand, dt)

    for node in topo:
        if node.is_variable:
            aval = var_aval(node)
            env[id(node)] = (aval,) if aval is not None else None
            continue
        ins = []
        unknown = False
        for src, idx in node.inputs:
            e = env.get(id(src))
            if e is None or idx >= len(e) or e[idx] is None:
                unknown = True
                break
            ins.append(e[idx])
        if unknown:
            try:
                solved = _solve_param_shapes(node, env)
            except Exception:
                solved = False
            if solved:
                ins = [env[id(src)][idx] for src, idx in node.inputs]
            else:
                env[id(node)] = None
                continue
        params = dict(node.attrs)
        if node.op.mode_dependent:
            params["_train"] = False
        if node.op.dynamic_params:
            for pname in node.op.dynamic_params:
                ins.append(jax.ShapeDtypeStruct((), _np.float32))
                params.pop(pname, None)
        if node.op.needs_rng:
            ins.append(jax.ShapeDtypeStruct((2,), _np.uint32))
        try:
            outv = jax.eval_shape(lambda *xs: node.op.fn(params, *xs), *ins)
        except Exception:
            env[id(node)] = None
            continue
        if not isinstance(outv, (tuple, list)):
            outv = (outv,)
        env[id(node)] = tuple(outv[:node.num_outputs()])
    return env


# ---------------------------------------------------------------------------
# scan-over-layers: repeated-subgraph isomorphism over the linear spine
# ---------------------------------------------------------------------------

# lower runs of >= SCAN_MIN_RUN identical segments; lint only complains
# about runs >= SCAN_HINT_RUN that could NOT lower (the compile-time win
# below 4 repeats rarely justifies a graph rewrite worth shouting about)
SCAN_MIN_RUN = 2
SCAN_HINT_RUN = 4


def _clean_cuts(ops, pos, heads):
    """Positions p where every op->op edge crossing the cut after ops[p]
    originates AT ops[p] — i.e. the graph's linear spine points.  `_topo`
    guarantees edges go earlier->later, so a clean cut means everything
    after it sees only ops[p]'s outputs (plus variables).  Graph heads
    act as virtual consumers past the end: a head produced mid-graph
    dirties every later cut, so a scanned run can never hide a value a
    caller reads."""
    n = len(ops)
    dirty = [False] * n
    spans = []
    for node in ops:
        j = pos[id(node)]
        for src, _ in node.inputs:
            if not src.is_variable:
                spans.append((pos[id(src)], j))
    for hnode, _ in heads:
        if not hnode.is_variable:
            spans.append((pos[id(hnode)], n))
    for i, j in spans:
        for p in range(i + 1, j):
            dirty[p] = True
    return [p for p in range(n) if not dirty[p]]


def _seg_signature(seg, seg_ids, prev_boundary, aux_ids):
    """Structural signature of one spine segment: op names + attrs +
    input wiring with node identities erased (local position / carry /
    param slot / aux slot).  Two segments with equal signatures are
    isomorphic layer bodies differing only in which parameters feed
    them."""
    seg_pos = {id(node): i for i, node in enumerate(seg)}
    params_order, aux_order = [], []
    param_slot, aux_slot = {}, {}
    rng_order = []
    sig = []
    for node in seg:
        naux = node.op.num_aux(node.attrs)
        n_in = len(node.inputs)
        enc = []
        for k, (src, idx) in enumerate(node.inputs):
            if src.is_variable:
                if naux and k >= n_in - naux and id(src) in aux_ids:
                    if id(src) not in aux_slot:
                        aux_slot[id(src)] = len(aux_order)
                        aux_order.append(src)
                    enc.append(("aux", aux_slot[id(src)]))
                else:
                    if id(src) not in param_slot:
                        param_slot[id(src)] = len(params_order)
                        params_order.append(src)
                    enc.append(("param", param_slot[id(src)]))
            elif id(src) in seg_ids:
                enc.append(("local", seg_pos[id(src)], idx))
            elif prev_boundary is not None and src is prev_boundary \
                    and idx == 0:
                enc.append(("carry",))
            else:
                # not the immediately-preceding boundary's output 0:
                # structurally unique, never joins a run
                enc.append(("extern", id(src), idx))
        if node.op.needs_rng:
            rng_order.append(node)
        sig.append((node.op.name,
                    tuple(sorted((str(k), str(v))
                                 for k, v in node.attrs.items())),
                    tuple(enc)))
    return tuple(sig), params_order, aux_order, rng_order


def _run_eligible(segments, params, auxs, head_nodes, var_consumers,
                  heads):
    """Why a run of equal-signature segments cannot lower, or None."""
    covered = {id(n) for seg in segments for n in seg}
    final_boundary = segments[-1][-1]
    for seg in segments:
        if seg[-1].num_outputs() != 1:
            return "multi-output block boundary"
    for seg in segments[:-1]:
        if id(seg[-1]) in head_nodes:
            return "intermediate block output is a graph head"
    for n_id in covered:
        if n_id in head_nodes and n_id != id(final_boundary):
            return "internal node is a graph head"
    for layer_vars in list(params) + list(auxs):
        for seg, v in zip(segments, layer_vars):
            seg_ids = {id(n) for n in seg}
            consumers = var_consumers.get(id(v), ())
            if any(id(c) not in seg_ids for c in consumers):
                return "parameter '%s' shared outside its layer" % v.name
            if any(h is v for h, _ in heads):
                return "parameter '%s' is a graph head" % v.name
    return None


def scan_plan(symbol, min_run=SCAN_MIN_RUN):
    """Detect runs of structurally identical layer blocks on the graph's
    linear spine — the repeated-subgraph isomorphism pass behind
    scan-over-layers lowering (`symbol.graph_eval_fn`) and the
    ``scan-opportunity`` lint.

    Returns ``{"runs": [...], "rejected": [...]}``.  Each run dict
    carries everything the evaluator needs to emit ONE `lax.scan` body
    over stacked per-layer parameters instead of N inlined copies:

    * ``length``    — layer count N
    * ``carry``     — (node, out_idx) feeding the first layer
    * ``boundary``  — final layer's output node (single-output)
    * ``segments``  — per-layer op node lists (topo order)
    * ``params``    — [slot][layer] parameter variable nodes
    * ``aux``       — [slot][layer] aux-state variable nodes
    * ``rng``       — [slot][layer] rng-consuming op nodes
    * ``covered``   — ids of every op the scan replaces

    Rejected entries ({"node", "length", "reason"}) are equal-signature
    runs that cannot lower (shared weights, exposed internals, ...) —
    the lint surfaces the ones >= SCAN_HINT_RUN."""
    topo = symbol._topo()
    ops = [n for n in topo if not n.is_variable]
    out = {"runs": [], "rejected": []}
    if len(ops) < 2 * max(min_run, 2):
        return out
    pos = {id(n): i for i, n in enumerate(ops)}
    aux_ids = symbol._aux_node_ids()
    heads = list(symbol._entries)
    head_nodes = {id(n) for n, _ in heads}
    var_consumers = {}
    for n in ops:
        for src, _ in n.inputs:
            if src.is_variable:
                var_consumers.setdefault(id(src), []).append(n)

    cuts = _clean_cuts(ops, pos, heads)
    if len(cuts) < 2:
        return out
    # segments between consecutive clean cuts (first segment starts at 0)
    segs, seg_meta = [], []
    start = 0
    for p in cuts:
        seg = ops[start:p + 1]
        prev_boundary = ops[start - 1] if start else None
        seg_ids = {id(n) for n in seg}
        sig, params_order, aux_order, rng_order = _seg_signature(
            seg, seg_ids, prev_boundary, aux_ids)
        segs.append(seg)
        seg_meta.append((sig, params_order, aux_order, rng_order))
        start = p + 1

    # A "layer" can span several unit segments (e.g. Conv+BN+Act between
    # three consecutive clean cuts): look for period-p repetition in the
    # unit-signature sequence, then re-derive the signature of each
    # MERGED layer segment exactly.  Unit-level equality is the cheap
    # filter; merged-level equality is the proof.
    m = len(segs)
    unit = [meta[0] for meta in seg_meta]
    max_p = max(1, min(8, m // max(min_run, 2)))
    candidates = []
    for p in range(1, max_p + 1):
        i = 0
        while i + 2 * p <= m:
            length = 1
            while i + (length + 1) * p <= m and \
                    unit[i + length * p:i + (length + 1) * p] == \
                    unit[i:i + p]:
                length += 1
            if length >= min_run:
                # coverage first, then the smaller period (one layer per
                # repetition, not two)
                candidates.append((length * p, -p, i, p, length))
                i += length * p
            else:
                i += 1
    taken = [False] * m
    runs_spec = []
    for _cov, _negp, i, p, length in sorted(candidates, reverse=True):
        if any(taken[i:i + length * p]):
            continue
        for q in range(i, i + length * p):
            taken[q] = True
        runs_spec.append((i, p, length))
    runs_spec.sort()

    for i, p, length in runs_spec:
        segments = [sum((segs[i + l * p + q] for q in range(p)), [])
                    for l in range(length)]
        metas = []
        ok = True
        for l in range(length):
            u0 = i + l * p
            prev_boundary = segs[u0 - 1][-1] if u0 else None
            seg = segments[l]
            metas.append(_seg_signature(seg, {id(n) for n in seg},
                                        prev_boundary, aux_ids))
            if metas[l][0] != metas[0][0]:
                ok = False
                break
        first = segments[0][0]
        if not ok or not any(e == ("carry",) for _, _, enc in metas[0][0]
                             for e in enc):
            out["rejected"].append({
                "node": first.name, "length": length,
                "reason": "layer bodies are not structurally identical "
                          "under the carry chain"})
            continue
        # [slot][layer] variable/rng nodes
        params = [[metas[l][1][s] for l in range(length)]
                  for s in range(len(metas[0][1]))]
        auxs = [[metas[l][2][s] for l in range(length)]
                for s in range(len(metas[0][2]))]
        rngs = [[metas[l][3][s] for l in range(length)]
                for s in range(len(metas[0][3]))]
        reason = _run_eligible(segments, params, auxs, head_nodes,
                               var_consumers, heads)
        if reason is None:
            carry_src = None
            seg0_ids = {id(n) for n in segments[0]}
            for src, idx in (inp for n in segments[0]
                             for inp in n.inputs):
                if not src.is_variable and id(src) not in seg0_ids:
                    carry_src = (src, idx)
                    break
            if carry_src is not None:
                out["runs"].append({
                    "length": length,
                    "carry": carry_src,
                    "boundary": segments[-1][-1],
                    "segments": segments,
                    "params": params,
                    "aux": auxs,
                    "rng": rngs,
                    "covered": {id(n) for seg in segments for n in seg},
                    "first": first,
                    "name": first.name,
                })
            else:
                out["rejected"].append({
                    "node": first.name, "length": length,
                    "reason": "no op-produced carry feeds the first "
                              "layer"})
        else:
            out["rejected"].append({"node": first.name,
                                    "length": length,
                                    "reason": reason})
    return out


def _pass_scan(symbol, topo):
    """scan-opportunity: a run of >= SCAN_HINT_RUN structurally identical
    blocks that the scan-over-layers lowering will NOT collapse — XLA
    still receives N inlined copies of the layer body."""
    out = []
    try:
        plan = scan_plan(symbol)
    except Exception:
        return out
    from .. import config as _config
    lowering_on = bool(_config.get("MXNET_FUSED_SCAN"))
    candidates = list(plan["rejected"])
    if not lowering_on:
        candidates += [{"node": r["name"], "length": r["length"],
                        "reason": "lowering disabled (MXNET_FUSED_SCAN=0)"}
                       for r in plan["runs"]]
    for rej in candidates:
        if rej["length"] < SCAN_HINT_RUN:
            continue
        node = next((n for n in topo if n.name == rej["node"]), None)
        f = Finding(
            "graph.scan", "scan-opportunity", HINT,
            "run of %d structurally identical blocks starting at '%s' "
            "did not lower to lax.scan (%s) — XLA compiles %d inlined "
            "copies of the layer body" % (rej["length"], rej["node"],
                                          rej["reason"], rej["length"]),
            node=rej["node"])
        if node is None or not _suppressed(node, "scan-opportunity"):
            out.append(f)
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check(symbol, shapes=None, hints=True, target=None):
    """Run the graph-pass catalog over a Symbol.

    Parameters
    ----------
    symbol : Symbol
    shapes : optional {var_name: shape} — enables the unbound-input pass
        and dtype propagation (same convention as `infer_shape` kwargs).
    hints : include perf hints (tpu-layout) alongside errors/warnings.
    """
    topo = symbol._topo()
    report = Report(target=target)
    report.extend(_pass_names(symbol, topo))
    report.extend(_pass_dead_outputs(symbol, topo))
    report.extend(_pass_aux(symbol, topo))
    env = {}
    try:
        env = _abstract_env(symbol, shapes)
    except Exception:
        env = {}
    report.extend(_pass_dtype(symbol, topo, env))
    if shapes:
        report.extend(_pass_unbound(symbol, topo, shapes))
    if hints:
        report.extend(_pass_layout(symbol, topo))
        report.extend(_pass_scan(symbol, topo))
    return report


def _json_structural(graph, target):
    """Passes that need the raw node table: duplicate names across the
    WHOLE file and nodes unreachable from any head (a Symbol object only
    ever holds reachable nodes, so these exist only for saved JSON)."""
    out = []
    nodes = graph.get("nodes", [])
    seen = {}
    for i, jn in enumerate(nodes):
        name = jn.get("name", "")
        if not str(name).strip():
            out.append(Finding("graph.names", "empty-name", ERROR,
                               f"node #{i} has an empty name", node=str(i),
                               location=target))
            continue
        if name in seen:
            out.append(Finding(
                "graph.names", "duplicate-name", ERROR,
                f"nodes #{seen[name]} and #{i} share the name '{name}'; "
                "loading this graph silently shadows one of them",
                node=name, location=target))
        else:
            seen[name] = i
    heads = [h[0] for h in graph.get("heads", [])]
    reachable = set()
    stack = list(heads)
    while stack:
        nid = stack.pop()
        if nid in reachable or nid >= len(nodes):
            continue
        reachable.add(nid)
        for inp in nodes[nid].get("inputs", []):
            stack.append(inp[0])
    for i, jn in enumerate(nodes):
        if i in reachable:
            continue
        is_var = jn.get("op") == "null"
        kind = "aux/argument state" if is_var else "op"
        out.append(Finding(
            "graph.aux" if is_var else "graph.dead",
            "unreachable-node", WARN,
            f"{kind} '{jn.get('name')}' (node #{i}) is not reachable from "
            "any graph head — dead " +
            ("state the loader will still allocate" if is_var
             else "compute"),
            node=jn.get("name"), location=target))
    return out


def check_json(text, shapes=None, hints=True, target=None):
    """Analyze a saved symbol JSON string: structural passes over the raw
    node table, then the Symbol passes over the loadable graph."""
    report = Report(target=target)
    try:
        graph = _json.loads(text)
    except ValueError as e:
        report.add(Finding("graph.names", "bad-json", ERROR,
                           f"not valid JSON: {e}", location=target))
        return report
    if not isinstance(graph, dict) or "nodes" not in graph:
        report.add(Finding("graph.names", "bad-json", ERROR,
                           "no 'nodes' table — not a symbol JSON",
                           location=target))
        return report
    report.extend(_json_structural(graph, target))
    try:
        from ..symbol.symbol import load_json
        sym = load_json(text)
    except Exception as e:
        report.add(Finding(
            "graph.names", "unloadable", ERROR,
            f"graph does not load ({str(e)[:160]}); only structural "
            "passes ran", location=target))
        return report
    # the structural pass already covered names over the WHOLE node table
    # (the Symbol walk sees only reachable nodes) — don't double-report
    sym_report = check(sym, shapes=shapes, hints=hints, target=target)
    report.extend(f for f in sym_report.findings
                  if f.pass_name != "graph.names")
    return report
