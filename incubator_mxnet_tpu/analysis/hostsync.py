"""Host-sync detection: who blocks the hot loop, and from which line.

On TPU a single `asnumpy()`/`asscalar()`/`wait_to_read()` inside the
training loop serializes the host with the device and can halve step
throughput — and it is invisible in a profile of *device* time.  When
analysis is enabled (MXNET_ANALYSIS=1 or `analysis.enable()`), the fit /
step hot loops mark themselves with `hot_loop(...)` and every blocking
read that happens inside one is attributed to the first stack frame
outside the data-plane modules — the metric, callback, or user line that
actually asked for the sync.

Findings dedupe on (kind, file, line) with a count, so a 10k-batch epoch
produces one finding per offending line, not 10k.
"""
from __future__ import annotations

import contextlib
import os
import sys
import threading

from .findings import Finding, WARN
from . import locks as _locks

__all__ = ["hot_loop", "note", "findings", "reset", "active", "CODES"]

# every code this pass emits (the findings.CODE_TABLE cross-check)
CODES = ("host-sync-in-loop",)

# modules whose frames are the sync MECHANISM, not its cause: attribution
# walks past them to the first caller outside the package data plane
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SKIP_SUFFIXES = (os.path.join("ndarray", "ndarray.py"), "engine.py",
                  os.path.join("analysis", "hostsync.py"))

_tls = threading.local()
_lock = _locks.make_lock("analysis.hostsync")
_findings = {}  # (kind, file, line) -> Finding

# module-level fast-path flag: NDArray.asnumpy checks this before paying
# for anything else.  It counts hot scopes across ALL threads (one
# thread leaving its loop must not blind another mid-epoch); the
# thread-local depth decides whether THIS thread's read is in a loop.
_active = 0


def active():
    return _active > 0 and getattr(_tls, "depth", 0) > 0


@contextlib.contextmanager
def hot_loop(label):
    """Mark a training hot loop (Module.fit's batch loop, Trainer.step).
    Blocking reads inside the scope are recorded; no-op unless analysis
    is enabled."""
    from . import enabled
    global _active
    if not enabled():
        yield
        return
    _tls.depth = getattr(_tls, "depth", 0) + 1
    _tls.label = label
    with _lock:
        _active += 1
    try:
        yield
    finally:
        _tls.depth -= 1
        with _lock:
            _active -= 1


@contextlib.contextmanager
def paused():
    """Suspend hot-loop attribution for this thread: epoch-boundary work
    (eval scoring, checkpoint gathers, epoch callbacks) legitimately
    blocks once per epoch and must not be reported as a per-batch
    hazard."""
    depth = getattr(_tls, "depth", 0)
    _tls.depth = 0
    try:
        yield
    finally:
        _tls.depth = depth


def _attribute():
    """file:line of the nearest caller outside the data-plane modules."""
    f = sys._getframe(2)  # skip _attribute and note
    while f is not None:
        fn = f.f_code.co_filename
        if not (fn.startswith(_PKG_DIR) and fn.endswith(_SKIP_SUFFIXES)):
            return fn, f.f_lineno
        f = f.f_back
    return "<unknown>", 0


def note(kind):
    """Record one blocking host read (call only when `active()`)."""
    if not active():
        return
    fname, lineno = _attribute()
    key = (kind, fname, lineno)
    label = getattr(_tls, "label", "hot loop")
    with _lock:
        f = _findings.get(key)
        if f is not None:
            f.count += 1
            return
        if len(_findings) >= 512:   # bounded: a pathological loop cannot
            return                  # grow the report without limit
        _findings[key] = Finding(
            "trace.hostsync", "host-sync-in-loop", WARN,
            f"{kind}() blocks the host inside {label}; on TPU this "
            "serializes dispatch with the device every batch (move the "
            "read out of the loop, or use a device-side metric)",
            location=f"{fname}:{lineno}")


def findings():
    with _lock:
        return list(_findings.values())


def reset():
    with _lock:
        _findings.clear()
