"""Finding/Report: the result currency of every analysis pass.

Analysis results are plain data — a pass never prints, raises, or mutates
the graph; it returns `Finding`s and the caller (CLI, `Module.check`,
the MXNET_ANALYSIS runtime report) decides how to surface them.  This is
the pass-infrastructure stance TVM and TF's grappler take (PAPERS.md):
analyses compose because their only output is a report.
"""
from __future__ import annotations

__all__ = ["Finding", "Report", "ERROR", "WARN", "HINT", "CODE_TABLE",
           "registered_codes", "code_info", "severity_rank"]

# severity ladder: errors break runs, warnings are correctness hazards,
# hints are perf advisories (padded-tile waste etc.) that a clean example
# graph may legitimately carry
ERROR = "error"
WARN = "warn"
HINT = "hint"

_SEV_RANK = {ERROR: 0, WARN: 1, HINT: 2}


def severity_rank(severity):
    """Lower rank = more severe (ERROR=0 < WARN=1 < HINT=2); the CLI's
    ``--fail-on`` threshold compares on this."""
    return _SEV_RANK[severity]


# ---------------------------------------------------------------------------
# THE finding-code registry: every code any pass emits, in one table, so
# `--json` output keys are a stable contract and docs/tests have a single
# source of truth.  One entry per code: (default severity, emitting
# pass names, one-line doc).  A code emitted by several subsystems
# (e.g. 'summary', 'host-lost') lists every pass; `duplicate-name` may
# escalate to ERROR at the emission site — the table records the
# DEFAULT.  tests/test_analysis.py asserts the table has no duplicate
# entries and no orphans (a table code no pass emits, or an emitted
# code the table misses).
# ---------------------------------------------------------------------------

def _build_code_table(rows):
    table = {}
    for code, severity, passes, doc in rows:
        if code in table:
            raise ValueError(f"finding code {code!r} registered twice")
        table[code] = (severity, tuple(passes), doc)
    return table


CODE_TABLE = _build_code_table([
    # -- graph passes (graph_passes.py) --------------------------------------
    ("duplicate-name", ERROR, ("graph.names",),
     "two distinct nodes share a name; bind/arg_dict silently shadow one"),
    ("empty-name", ERROR, ("graph.names",),
     "node has an empty name and cannot be addressed"),
    ("bad-json", ERROR, ("graph.names",),
     "file is not a loadable symbol JSON"),
    ("unloadable", ERROR, ("graph.names",),
     "symbol JSON parses but does not load; only structural passes ran"),
    ("dead-output", WARN, ("graph.dead",),
     "multi-output op output computed, shipped through XLA, never used"),
    ("unreachable-node", WARN, ("graph.dead", "graph.aux"),
     "saved-JSON node unreachable from any head (dead compute/state)"),
    ("shared-aux", WARN, ("graph.aux",),
     "one running-stat variable feeds several ops' aux slots (racing)"),
    ("aux-as-input", WARN, ("graph.aux",),
     "aux state also consumed as a regular input (updated under reader)"),
    ("f64-promotion", WARN, ("graph.dtype",),
     "float64 introduced; TPUs have no f64 ALU (emulation or demotion)"),
    ("f64-output", WARN, ("graph.dtype",),
     "the f64 promotion reaches graph outputs; consumers inherit it"),
    ("unbound-input", WARN, ("graph.unbound",),
     "variable shape not inferable from inputs/attrs; bind fails there"),
    ("tpu-layout", HINT, ("graph.layout",),
     "feature dim off the 8/128 tile grid pads the MXU tile"),
    ("scan-opportunity", HINT, ("graph.scan",),
     "run of >=4 structurally identical blocks did not lower to "
     "lax.scan; XLA compiles N inlined copies of the layer body"),
    # -- script AST lints (source_lint.py) -----------------------------------
    ("syntax-error", WARN, ("source.parse",),
     "script does not parse; nothing else was checked"),
    ("host-sync-in-loop", WARN, ("source.hostsync", "trace.hostsync"),
     "blocking host read inside a hot loop (asnumpy/asscalar/waitall)"),
    ("kvstore-local-on-tpu", WARN, ("source.kvstore",),
     "kvstore='local' in a TPU script reduces gradients through host"),
    ("unbucketed-push", WARN, ("source.kvstore",),
     "per-parameter kv.push/pull in a loop; batch the full key list"),
    ("unbounded-retry", WARN, ("source.retry",),
     "while-True retry with no deadline/raise spins on a dead peer"),
    ("bare-except", WARN, ("source.except",),
     "bare/blanket except swallows MXNetError incl. failover signals"),
    ("nan-swallow", WARN, ("source.guardian",),
     "hand-rolled NaN tolerance around a training update; use the "
     "guardian"),
    ("unsupervised-collective", WARN, ("source.supervisor",),
     "host-level collective outside a supervisor/watchdog scope"),
    ("router-bypass", WARN, ("source.router",),
     "direct ServedModel/ModelServer use bypasses the configured router"),
    ("unguarded-model-swap", WARN, ("source.loop",),
     "direct swap_weights/replica.swap in a LoopController script "
     "bypasses the canary gate; publish to the ModelRegistry instead"),
    ("fixed-fleet", WARN, ("source.fleet",),
     "hand-pinned replica list in an autoscaler-configured script"),
    ("host-transfer-in-graph", WARN, ("source.hostsync",),
     "np coercion / device_get inside a jit-decorated function stalls "
     "the device pipeline every call"),
    ("unnamed-thread", WARN, ("source.thread",),
     "Thread() without name=; findings/trace events attribute by name"),
    ("bare-acquire", WARN, ("source.locks",),
     "statement-level lock.acquire() leaks the lock on exceptions"),
    ("sleep-under-lock", WARN, ("source.locks",),
     "time.sleep inside a lock scope parks every queued thread"),
    ("unjoined-thread-in-init", WARN, ("source.thread",),
     "class starts a Thread but registers no lifecycle method"),
    ("untracked-stats", WARN, ("source.obs",),
     "public stats() dict not registered with the obs MetricsRegistry; "
     "invisible to the scrape plane"),
    ("dense-grad-for-embedding", WARN, ("source.embedding",),
     "training loop pushes the full dense gradient of an embedding-"
     "shaped parameter; push row_sparse so only touched rows move"),
    ("blocking-h2d-in-loop", WARN, ("source.io",),
     "blocking device_put/as_in_context feed inside a training loop; "
     "the h2d staging ring (MXNET_IO_RING) overlaps the transfer"),
    ("kv-cache-recompile", WARN, ("source.decode",),
     "KV cache grown by concatenate in a decode loop recompiles every "
     "step; preallocate fixed-shape + dynamic_update_slice "
     "(serving.DecodeEngine)"),
    # -- runtime trace passes ------------------------------------------------
    ("shape-churn", WARN, ("trace.recompile",),
     "new jit signature forced a fresh XLA compile (ragged batches etc.)"),
    # -- mxtsan concurrency sanitizer (tsan.py) ------------------------------
    ("lock-order-inversion", ERROR, ("tsan.lockorder",),
     "two locks acquired in both orders by different threads"),
    ("lock-order-cycle", ERROR, ("tsan.lockorder",),
     "cycle in the lock-acquisition-order graph (deadlockable)"),
    ("shared-state-race", WARN, ("tsan.race",),
     "unsynchronized write on registered shared state (lockset empty)"),
    ("blocking-under-lock", WARN, ("tsan.blocking",),
     "blocking call while holding a contended lock"),
    ("leaked-thread", WARN, ("tsan.lifecycle",),
     "non-daemon thread never joined; wedges interpreter shutdown"),
    ("thread-outlives-close", WARN, ("tsan.lifecycle",),
     "thread still alive after its owner's close() returned"),
    ("join-no-timeout", WARN, ("tsan.lifecycle",),
     "join() without timeout in package code blocks shutdown forever"),
    # -- program cache / kvstore / resilience / fleet summaries --------------
    ("summary", HINT, ("cache.programs", "kvstore.buckets",
                       "serving.fleet"),
     "per-subsystem runtime summary (cache traffic, bucket economy, "
     "fleet scale events)"),
    ("churn-compiles", WARN, ("cache.programs",),
     "one program compiled under several signatures (shape churn cost)"),
    ("skip-batch", WARN, ("guardian.skip",),
     "guardian refused a non-finite step in-graph; batch quarantined"),
    ("rollback", WARN, ("guardian.rollback",),
     "loss spike rolled training back to the newest healthy checkpoint"),
    ("spike-unrecoverable", WARN, ("guardian.spike",),
     "loss spike with no checkpoint_dir to roll back to"),
    ("host-lost", WARN, ("supervisor.host", "serving.fleet"),
     "a pod/fleet host stopped heartbeating and was declared dead"),
    ("straggler-host", WARN, ("supervisor.straggler",),
     "host step-time EWMA diverges k-sigma from the pod median"),
    ("backfill", WARN, ("serving.fleet",),
     "fleet backfilled to target after capacity loss"),
    ("cold-spinup", WARN, ("serving.fleet",),
     "scale-up compiled XLA programs; warm spinup should be zero-compile"),
    # -- mxcost static cost analysis (cost.py / budgets.py) ------------------
    ("cost-summary", HINT, ("cost.roofline",),
     "per-program flops/bytes/AI, roofline bound, step lower bound, "
     "peak HBM"),
    ("dequant-fp32-dot", WARN, ("cost.dtype",),
     "dequantized values reach a dot computing in fp32 (the "
     "int8-slower-than-fp32 static signature)"),
    ("quantized-fp32-compute", WARN, ("cost.dtype",),
     "quantized dot-class op registers float32 compute (no int8 MXU "
     "rate)"),
    ("f32-upcast-in-bf16", WARN, ("cost.dtype",),
     "bf16->f32 upcast feeds an fp32 dot inside a bf16-dominant graph"),
    ("hidden-host-transfer", WARN, ("cost.host",),
     "callback primitive inside a traced program crosses to the host "
     "every step"),
    ("donation-opportunity", HINT, ("cost.memory",),
     "step-boundary buffer dies undonated; donation would reuse it "
     "in place"),
    ("collective-summary", HINT, ("cost.collectives",),
     "statically derived collectives/bytes per step for a mesh plan"),
    ("collective-o-params", WARN, ("cost.collectives",),
     "plan dispatches one collective per parameter (bucket economy "
     "broken)"),
    ("budget-regression", ERROR, ("cost.budget",),
     "metric exceeds the committed COST_BUDGETS baseline (CI fails)"),
    ("budget-missing", HINT, ("cost.budget",),
     "program/plan has no baseline entry; snapshot it"),
    ("budget-slack", HINT, ("cost.budget",),
     "metric is well under budget; re-snapshot to tighten the gate"),
    # -- mxshard static SPMD sharding analyzer (sharding.py) -----------------
    ("implicit-replication", WARN, ("shard.memory",),
     "param/activation >= MXNET_SHARD_MIN_MB fully replicated while "
     "the mesh has a >1-device non-batch axis (per-device HBM blowup)"),
    ("hidden-reshard", WARN, ("shard.propagate",),
     "edge whose producer/consumer PartitionSpecs differ; GSPMD "
     "inserts an all-gather/all-to-all/slice the cost model must "
     "account for"),
    ("rule-coverage", ERROR, ("shard.rules",),
     "param matches zero or >=2 sharding rules of a rule set that "
     "applies to the model; it silently replicates or is ambiguous"),
    ("dp-axis-leak", WARN, ("shard.propagate",),
     "batch-led activation lost its dim-0 dp sharding past the input; "
     "every device computes the full batch downstream"),
    ("shard-fallback", HINT, ("shard.propagate",),
     "op has no propagation rule; outputs assumed replicated (costs "
     "become upper bounds there)"),
    ("shard-summary", HINT, ("shard.summary",),
     "per-program sharding summary: per-device peak HBM, tp/GSPMD "
     "collectives, reshard edges, fallback ops"),
    ("unsharded-device-put", WARN, ("source.sharding",),
     "device_put/as_in_context of a multi-MB array inside a mesh-"
     "configured scope without a sharding argument replicates it on "
     "every device"),
])


def registered_codes():
    """{code: (default severity, passes, doc)} — a copy of the table."""
    return dict(CODE_TABLE)


def code_info(code):
    """(default severity, passes, doc) for a registered code, or None."""
    return CODE_TABLE.get(code)


class Finding:
    """One diagnostic: what pass fired, where, and why."""

    __slots__ = ("pass_name", "code", "severity", "message", "node",
                 "location", "count")

    def __init__(self, pass_name, code, severity, message, node=None,
                 location=None):
        self.pass_name = pass_name    # e.g. "graph.names", "trace.hostsync"
        self.code = code              # stable slug, e.g. "duplicate-name"
        self.severity = severity      # ERROR | WARN | HINT
        self.message = message
        self.node = node              # graph node name, when graph-scoped
        self.location = location      # "file:line" when source-scoped
        self.count = 1                # occurrences (hostsync dedupes here)

    def format(self):
        where = self.location or (f"node '{self.node}'" if self.node else "")
        times = f" (x{self.count})" if self.count > 1 else ""
        head = f"{where}: " if where else ""
        return f"{head}{self.severity} [{self.code}] {self.message}{times}"

    def __repr__(self):
        return f"<Finding {self.format()}>"

    def as_dict(self):
        return {"pass": self.pass_name, "code": self.code,
                "severity": self.severity, "message": self.message,
                "node": self.node, "location": self.location,
                "count": self.count}


class Report:
    """An ordered collection of findings with filtering/summary helpers."""

    def __init__(self, findings=(), target=None):
        self.findings = list(findings)
        self.target = target  # what was analyzed (symbol name, file, ...)

    def add(self, finding):
        self.findings.append(finding)

    def extend(self, findings):
        self.findings.extend(findings)

    def __iter__(self):
        return iter(self.findings)

    def __len__(self):
        return len(self.findings)

    def __bool__(self):
        return bool(self.findings)

    def filter(self, max_severity=HINT, codes=None):
        """Findings at or above a severity (ERROR < WARN < HINT ordering),
        optionally restricted to a code set."""
        keep = [f for f in self.findings
                if _SEV_RANK[f.severity] <= _SEV_RANK[max_severity]
                and (codes is None or f.code in codes)]
        return Report(keep, target=self.target)

    def suppress(self, codes):
        """Drop findings whose code is in `codes` (CLI --suppress)."""
        codes = set(codes)
        return Report([f for f in self.findings if f.code not in codes],
                      target=self.target)

    def by_code(self):
        out = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def by_pass(self):
        out = {}
        for f in self.findings:
            out[f.pass_name] = out.get(f.pass_name, 0) + 1
        return out

    def format(self):
        prefix = f"{self.target}: " if self.target else ""
        return "\n".join(prefix + f.format() for f in self.findings)
