"""Finding/Report: the result currency of every analysis pass.

Analysis results are plain data — a pass never prints, raises, or mutates
the graph; it returns `Finding`s and the caller (CLI, `Module.check`,
the MXNET_ANALYSIS runtime report) decides how to surface them.  This is
the pass-infrastructure stance TVM and TF's grappler take (PAPERS.md):
analyses compose because their only output is a report.
"""
from __future__ import annotations

__all__ = ["Finding", "Report", "ERROR", "WARN", "HINT"]

# severity ladder: errors break runs, warnings are correctness hazards,
# hints are perf advisories (padded-tile waste etc.) that a clean example
# graph may legitimately carry
ERROR = "error"
WARN = "warn"
HINT = "hint"

_SEV_RANK = {ERROR: 0, WARN: 1, HINT: 2}


class Finding:
    """One diagnostic: what pass fired, where, and why."""

    __slots__ = ("pass_name", "code", "severity", "message", "node",
                 "location", "count")

    def __init__(self, pass_name, code, severity, message, node=None,
                 location=None):
        self.pass_name = pass_name    # e.g. "graph.names", "trace.hostsync"
        self.code = code              # stable slug, e.g. "duplicate-name"
        self.severity = severity      # ERROR | WARN | HINT
        self.message = message
        self.node = node              # graph node name, when graph-scoped
        self.location = location      # "file:line" when source-scoped
        self.count = 1                # occurrences (hostsync dedupes here)

    def format(self):
        where = self.location or (f"node '{self.node}'" if self.node else "")
        times = f" (x{self.count})" if self.count > 1 else ""
        head = f"{where}: " if where else ""
        return f"{head}{self.severity} [{self.code}] {self.message}{times}"

    def __repr__(self):
        return f"<Finding {self.format()}>"

    def as_dict(self):
        return {"pass": self.pass_name, "code": self.code,
                "severity": self.severity, "message": self.message,
                "node": self.node, "location": self.location,
                "count": self.count}


class Report:
    """An ordered collection of findings with filtering/summary helpers."""

    def __init__(self, findings=(), target=None):
        self.findings = list(findings)
        self.target = target  # what was analyzed (symbol name, file, ...)

    def add(self, finding):
        self.findings.append(finding)

    def extend(self, findings):
        self.findings.extend(findings)

    def __iter__(self):
        return iter(self.findings)

    def __len__(self):
        return len(self.findings)

    def __bool__(self):
        return bool(self.findings)

    def filter(self, max_severity=HINT, codes=None):
        """Findings at or above a severity (ERROR < WARN < HINT ordering),
        optionally restricted to a code set."""
        keep = [f for f in self.findings
                if _SEV_RANK[f.severity] <= _SEV_RANK[max_severity]
                and (codes is None or f.code in codes)]
        return Report(keep, target=self.target)

    def suppress(self, codes):
        """Drop findings whose code is in `codes` (CLI --suppress)."""
        codes = set(codes)
        return Report([f for f in self.findings if f.code not in codes],
                      target=self.target)

    def by_code(self):
        out = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def by_pass(self):
        out = {}
        for f in self.findings:
            out[f.pass_name] = out.get(f.pass_name, 0) + 1
        return out

    def format(self):
        prefix = f"{self.target}: " if self.target else ""
        return "\n".join(prefix + f.format() for f in self.findings)
