"""mxtsan — an opt-in runtime concurrency sanitizer (MXNET_TSAN=1).

PRs 4-8 made this a genuinely concurrent system: router health loops,
replica dispatch threads, MicroBatcher workers, supervisor heartbeat and
watchdog threads, membership tables, async checkpoint writers.  The bug
class most likely to take a serving fleet or a pod down — a lock-order
deadlock, a racy shared counter, a leaked thread wedging shutdown — is
invisible to mxlint's graph/AST passes because it only exists at
runtime, between threads.  This module is the runtime half of the
concurrency tier (the AST half lives in `source_lint`): it watches the
instrumented primitives that `analysis.locks` hands out and turns
hazards into ordinary `Finding`s *before* they hang anything.

Four passes, all feeding `analysis.runtime_report()` /
`tools/mxlint.py --tsan-report`:

* **lock-order graph** (`lock-order-inversion` / `lock-order-cycle`,
  error) — every instrumented acquire records "lock B taken while
  holding lock A" edges into one process-wide graph, keyed by lock
  *name* (instances of the same pool share a node, self-edges are
  ignored).  A new edge that closes a cycle is a potential deadlock and
  is reported immediately, naming both locks, both threads, and the two
  `file:line` acquisition sites — the evidence a hang would never give
  you.  `MXNET_TSAN_RAISE=1` escalates the finding to an `MXNetError`
  at the acquisition site.

* **shared-state race attribution** (`shared-state-race`, warn) —
  objects registered with `instrument(obj, name)` (attribute writes)
  and dicts built with `shared_dict(name)` (item reads + writes) carry
  an Eraser-style lockset check: a key starts EXCLUSIVE to its creating
  thread (initialization writes never report); the first access by a
  second thread seeds the candidate lockset, every later access
  intersects its held locks in, and the set going empty with a write
  involved in the shared epoch is an unsynchronized write/write or
  write/read pair, reported with both threads and both exact sites.
  Publish-then-read-only data stays silent; state ordered by
  happens-before alone (handed across a queue) should not be
  registered.

* **blocking-call-under-lock** (`blocking-under-lock`, warn) —
  `time.sleep` and blocking `queue.Queue.get` are patched while the
  sanitizer is on, and `dist.transport` reports its socket waits via
  `note_blocking("socket.recv")`; any of them arriving while the
  calling thread holds an instrumented lock that other threads also
  take with BLOCKING acquires (contended — a token only ever
  try-acquired, like a swap-in-progress guard, can never park a
  waiter) is reported: that is a thread parking itself on a slow call
  while everyone else queues on the lock.

* **thread lifecycle** (`leaked-thread` / `thread-outlives-close` /
  `join-no-timeout`, warn) — `threading.Thread.start`/`join` are
  patched to record creation sites.  `findings()` reports non-daemon
  threads (created by this repo's code or its tests, never by
  third-party libraries) still alive and unjoined; `join_thread(t,
  timeout, owner=...)` is the audited close-path join — a thread that
  survives it is reported as outliving its owner's `close()`; a
  package-internal `join()` with no timeout in a drain path is flagged
  at its call site.

Zero-overhead stance: nothing in this module runs unless
``MXNET_TSAN=1`` (or `tsan.enable()`).  With the flag unset,
`analysis.locks.make_lock` returns plain `threading.Lock` objects and
no patch is installed — the hot paths are byte-identical to the
pre-sanitizer build.  ``MXNET_TSAN_LOG=path`` dumps findings plus the
lock-order graph as JSON at process exit (the artifact
``mxlint --tsan-report`` renders).
"""
from __future__ import annotations

import atexit
import os
import sys
import threading
import time

from .findings import Finding, Report, ERROR, WARN

__all__ = ["enabled", "enable", "disable", "findings", "report", "reset",
           "dump", "lock_graph", "instrument", "shared_dict",
           "note_blocking", "join_thread", "TsanLock", "TsanRLock",
           "make_condition", "CODES"]

# every code this sanitizer emits (the findings.CODE_TABLE cross-check)
CODES = ("lock-order-inversion", "lock-order-cycle", "shared-state-race",
         "blocking-under-lock", "leaked-thread", "thread-outlives-close",
         "join-no-timeout")

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)
# frames inside these files are sanitizer/lock mechanism, not the code
# under analysis; site attribution walks past them
_SKIP_BASENAMES = ("tsan.py", "locks.py", "threading.py", "queue.py")

_enabled = None          # tri-state: None = read MXNET_TSAN lazily
_installed = False

# all sanitizer bookkeeping lives under ONE private raw lock (never an
# instrumented one: the sanitizer must not sanitize itself)
_state_lock = threading.Lock()
_tls = threading.local()

_lock_infos = {}         # name -> _LockInfo (instances share a node)
_edges = {}              # (a_name, b_name) -> edge record dict
_adj = {}                # a_name -> set(b_name)
_accesses = {}           # (state, key) -> {thread_name: {"write"/"read": (held, site)}}
_threads = {}            # Thread -> {"site", "daemon", "joined"}
_findings = {}           # dedup key -> Finding
_MAX_FINDINGS = 512
_MAX_ACCESS_KEYS = 8192
_MAX_THREADS = 4096

_orig = {}               # patched callables, for disable()


# -- enablement ---------------------------------------------------------------

def enabled():
    """Whether the sanitizer is active (MXNET_TSAN, read lazily)."""
    global _enabled
    if _enabled is None:
        from .. import config as _config
        _enabled = bool(_config.get("MXNET_TSAN"))
        if _enabled:
            _install()
    return _enabled


def enable():
    """Turn the sanitizer on programmatically (tests; equivalent to
    MXNET_TSAN=1 for locks/state created *after* this call)."""
    global _enabled
    _enabled = True
    _install()


def disable():
    """Turn the sanitizer off and remove the blocking/lifecycle patches.
    Already-instrumented locks keep working (they just stop being
    created); recorded findings survive until `reset()`."""
    global _enabled
    _enabled = False
    _uninstall()


def _raise_on_deadlock():
    from .. import config as _config
    try:
        return bool(_config.get("MXNET_TSAN_RAISE"))
    except Exception:
        return False


def _install():
    global _installed
    with _state_lock:
        if _installed:
            return
        _installed = True
    import queue as _queue
    _orig["sleep"] = time.sleep
    _orig["queue_get"] = _queue.Queue.get
    _orig["thread_start"] = threading.Thread.start
    _orig["thread_join"] = threading.Thread.join

    def _sleep(seconds):
        if seconds and seconds > 0:
            note_blocking("time.sleep", detail=f"{seconds:g}s")
        return _orig["sleep"](seconds)

    def _get(self, block=True, timeout=None):
        if block:
            note_blocking("queue.get",
                          detail="no timeout" if timeout is None
                          else f"timeout={timeout:g}s")
        return _orig["queue_get"](self, block, timeout)

    def _start(self):
        with _state_lock:
            if len(_threads) < _MAX_THREADS:
                _threads[self] = {"site": _site(), "daemon": self.daemon,
                                  "joined": False}
        return _orig["thread_start"](self)

    def _join(self, timeout=None):
        rec = _threads.get(self)
        if rec is not None:
            rec["joined"] = True
        if timeout is None:
            site = _site()
            if _ours(site) and _PKG_DIR in os.path.abspath(
                    site.rsplit(":", 1)[0]):
                _add_finding(
                    "lifecycle", "join-no-timeout", WARN,
                    f"join() with no timeout on thread "
                    f"'{self.name}': a wedged thread blocks this "
                    "shutdown/drain path forever — join with a timeout "
                    "and surface the leak (tsan.join_thread does both)",
                    location=site, key=("join-no-timeout", site))
        return _orig["thread_join"](self, timeout)

    time.sleep = _sleep
    _queue.Queue.get = _get
    threading.Thread.start = _start
    threading.Thread.join = _join

    from .. import config as _config
    log = _config.get("MXNET_TSAN_LOG")
    if log:
        atexit.register(dump, log)


def _uninstall():
    global _installed
    with _state_lock:
        if not _installed:
            return
        _installed = False
    import queue as _queue
    time.sleep = _orig.pop("sleep", time.sleep)
    if "queue_get" in _orig:
        _queue.Queue.get = _orig.pop("queue_get")
    if "thread_start" in _orig:
        threading.Thread.start = _orig.pop("thread_start")
    if "thread_join" in _orig:
        threading.Thread.join = _orig.pop("thread_join")


# -- shared helpers -----------------------------------------------------------

def _site():
    """file:line of the nearest frame outside the sanitizer machinery."""
    f = sys._getframe(2)
    while f is not None:
        base = os.path.basename(f.f_code.co_filename)
        if base not in _SKIP_BASENAMES:
            return f"{f.f_code.co_filename}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


def _ours(site):
    """Whether a site belongs to this repo (package, tests, tools) as
    opposed to the stdlib or site-packages — third-party threads and
    joins are not this sanitizer's business."""
    path = site.rsplit(":", 1)[0]
    if "site-packages" in path or "dist-packages" in path:
        return False
    return os.path.abspath(path).startswith(_REPO_DIR)


def _held():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _add_finding(pass_suffix, code, severity, message, location=None,
                 key=None):
    with _state_lock:
        k = key if key is not None else (code, location)
        f = _findings.get(k)
        if f is not None:
            f.count += 1
            return f
        if len(_findings) >= _MAX_FINDINGS:
            return None
        f = Finding(f"tsan.{pass_suffix}", code, severity, message,
                    location=location)
        _findings[k] = f
        return f


# -- lock instrumentation -----------------------------------------------------

class _LockInfo:
    __slots__ = ("name", "threads", "blocking_threads")

    def __init__(self, name):
        self.name = name
        self.threads = set()     # names of threads that ever acquired it
        # threads that acquired it with blocking=True: a lock only ever
        # TRY-acquired (a swap-in-progress token, a poll) can never park
        # a waiter, so it must not feed the blocking-under-lock pass
        self.blocking_threads = set()

    @property
    def contended(self):
        return len(self.blocking_threads) > 1


def _register_lock(name):
    name = name or "anonymous"
    with _state_lock:
        info = _lock_infos.get(name)
        if info is None:
            info = _lock_infos[name] = _LockInfo(name)
        return info


def _note_acquired(info, reentry=False, blocking=True):
    """Track one acquisition; returns an error message when this
    acquisition closed a NEW lock-order cycle and MXNET_TSAN_RAISE is
    set (the caller releases the lock and raises at the site)."""
    site = _site()
    held = _held()
    tname = threading.current_thread().name
    with _state_lock:
        info.threads.add(tname)
        if blocking:
            info.blocking_threads.add(tname)
    err = None
    if not reentry:
        for h_info, h_site in held:
            if h_info.name != info.name:
                e = _add_edge(h_info, h_site, info, site, tname)
                err = err or e
    held.append((info, site))
    return err


def _note_released(info):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is info:
            del held[i]
            return


def _add_edge(a_info, a_site, b_info, b_site, tname):
    """Record lock-order edge a -> b; closing a cycle is a potential
    deadlock, reported (and optionally raised) at this acquisition."""
    a, b = a_info.name, b_info.name
    with _state_lock:
        if (a, b) in _edges:
            _edges[(a, b)]["count"] += 1
            return
        _edges[(a, b)] = {"from": a, "to": b, "thread": tname,
                          "held_at": a_site, "acquired_at": b_site,
                          "count": 1}
        _adj.setdefault(a, set()).add(b)
        # does b already reach a?  DFS over the name-level graph
        path = _find_path(b, a)
    if path is None:
        return None
    path = path + [a]   # the full cycle's node list (b ... a)
    if len(path) == 2:
        other = _edges.get((b, a), {})
        msg = (f"lock-order inversion between '{a}' and '{b}': thread "
               f"'{tname}' acquires '{b}' at {b_site} while holding "
               f"'{a}' (taken at {a_site}), but thread "
               f"'{other.get('thread', '?')}' acquires '{a}' at "
               f"{other.get('acquired_at', '?')} while holding '{b}' "
               f"(taken at {other.get('held_at', '?')}) — run these two "
               "paths concurrently and both threads wait forever")
        code = "lock-order-inversion"
    else:
        chain = " -> ".join(path + [path[0]])
        msg = (f"lock-order cycle {chain}: thread '{tname}' closed it by "
               f"acquiring '{b}' at {b_site} while holding '{a}' (taken "
               f"at {a_site}) — some interleaving of the threads on this "
               "cycle deadlocks")
        code = "lock-order-cycle"
    f = _add_finding("lockorder", code, ERROR, msg, location=b_site,
                     key=(code, frozenset(path)))
    if f is not None and f.count == 1 and _raise_on_deadlock():
        return f"MXNET_TSAN_RAISE: {msg}"
    return None


def _find_path(src, dst):
    """Name-level DFS src -> dst; returns the node path or None.
    Caller holds _state_lock."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            if nxt == dst:
                return path
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class TsanLock:
    """Instrumented non-reentrant lock (`analysis.locks.make_lock`)."""

    __slots__ = ("_lock", "_info")

    def __init__(self, name=None):
        self._lock = threading.Lock()
        self._info = _register_lock(name)

    @property
    def name(self):
        return self._info.name

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            err = _note_acquired(self._info, blocking=blocking)
            if err is not None:
                # escalation mode: surface the deadlock at its site,
                # WITHOUT leaving the lock held behind the exception
                _note_released(self._info)
                self._lock.release()
                from ..base import MXNetError
                raise MXNetError(err)
        return ok

    def release(self):
        _note_released(self._info)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()   # mxlint: disable=bare-acquire (wrapper mechanics)
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<TsanLock '{self._info.name}'>"


class TsanRLock:
    """Instrumented reentrant lock.  Exposes the `_is_owned` /
    `_release_save` / `_acquire_restore` trio so `threading.Condition`
    can wrap it, with held-stack bookkeeping kept consistent across
    `wait()`'s full release."""

    __slots__ = ("_lock", "_info", "_depth_by_thread")

    def __init__(self, name=None):
        self._lock = threading.RLock()
        self._info = _register_lock(name)
        self._depth_by_thread = {}

    @property
    def name(self):
        return self._info.name

    def _depth(self, delta):
        ident = threading.get_ident()
        d = self._depth_by_thread.get(ident, 0) + delta
        if d <= 0:
            self._depth_by_thread.pop(ident, None)
        else:
            self._depth_by_thread[ident] = d
        return d

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            reentry = self._depth(+1) > 1
            err = _note_acquired(self._info, reentry=reentry,
                                 blocking=blocking)
            if err is not None:
                self._depth(-1)
                _note_released(self._info)
                self._lock.release()
                from ..base import MXNetError
                raise MXNetError(err)
        return ok

    def release(self):
        self._depth(-1)
        _note_released(self._info)
        self._lock.release()

    # Condition protocol ------------------------------------------------------
    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        ident = threading.get_ident()
        depth = self._depth_by_thread.pop(ident, 0)
        for _ in range(max(depth, 1)):
            _note_released(self._info)
        return self._lock._release_save(), depth

    def _acquire_restore(self, state):
        inner, depth = state
        self._lock._acquire_restore(inner)
        for i in range(max(depth, 1)):
            _note_acquired(self._info, reentry=i > 0)
        ident = threading.get_ident()
        self._depth_by_thread[ident] = max(depth, 1)

    def __enter__(self):
        self.acquire()   # mxlint: disable=bare-acquire (wrapper mechanics)
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<TsanRLock '{self._info.name}'>"


def make_condition(lock=None, name=None):
    """An instrumented `threading.Condition` (its lock participates in
    the order graph and lockset checks)."""
    if lock is None:
        lock = TsanRLock(name)
    return threading.Condition(lock)


# -- shared-state race attribution -------------------------------------------

def _held_names():
    return frozenset(info.name for info, _ in _held())


def _access(state, key, kind):
    """Eraser-style lockset check for one access to `state[key]`.

    Each key starts EXCLUSIVE to its creating thread (initialization
    writes are ordered-before publication and never report).  The first
    access by a second thread moves it SHARED and seeds the candidate
    lockset from that access; every later access intersects its held
    set in.  A report fires when the lockset goes empty while a write
    is involved *in the shared epoch* — publish-then-read-only data
    stays silent, a genuinely unsynchronized write/write or write/read
    pair is attributed to both threads' exact sites."""
    site = _site()
    held = _held_names()
    tname = threading.current_thread().name
    race = None
    with _state_lock:
        k = (state, key)
        rec = _accesses.get(k)
        if rec is None:
            if len(_accesses) >= _MAX_ACCESS_KEYS:
                return
            rec = _accesses[k] = {"owner": tname, "shared": False,
                                  "written_shared": False,
                                  "lockset": None, "entries": {}}
        entries = rec["entries"]
        if not rec["shared"] and tname == rec["owner"]:
            entries.setdefault(tname, {})[kind] = (held, site)
            return
        if not rec["shared"]:
            rec["shared"] = True
            rec["lockset"] = set(held)
        else:
            rec["lockset"] &= held
        fire = not rec["lockset"] and \
            (kind == "write" or rec["written_shared"])
        if kind == "write":
            rec["written_shared"] = True
        if fire:
            # attribute: another thread's most recent conflicting access
            # sharing no lock with this one (prefer its writes)
            for other_t, kinds in entries.items():
                if other_t == tname:
                    continue
                order = ("write",) if kind != "write" else \
                    ("write", "read")
                for other_kind in order:
                    entry = kinds.get(other_kind)
                    if entry is None:
                        continue
                    o_held, o_site = entry
                    if held & o_held:
                        continue
                    race = (other_t, other_kind, o_held, o_site)
                    break
                if race is not None:
                    break
        entries.setdefault(tname, {})[kind] = (held, site)
    if race is None:
        return
    other_t, other_kind, o_held, o_site = race
    field = f"{state}[{key!r}]" if key is not None else state
    what = "write/write" if (kind == "write" and other_kind == "write") \
        else "write/read"
    fmt = lambda s: "{" + ", ".join(sorted(s)) + "}" if s else "no lock"
    _add_finding(
        "race", "shared-state-race", WARN,
        f"unsynchronized {what} on shared state {field}: thread "
        f"'{tname}' {kind}s at {site} holding {fmt(held)}; thread "
        f"'{other_t}' {other_kind}s at {o_site} holding {fmt(o_held)} — "
        "no common lock orders these accesses",
        location=site,
        key=("shared-state-race", state, key, frozenset((site, o_site))))


class _SharedDict(dict):
    """Race-tracked dict: item reads and writes feed the lockset check."""

    def _tsan(self, key, kind):
        _access(getattr(self, "_tsan_state_name", "dict"), key, kind)

    def __getitem__(self, key):
        self._tsan(key, "read")
        return dict.__getitem__(self, key)

    def get(self, key, default=None):
        self._tsan(key, "read")
        return dict.get(self, key, default)

    def __contains__(self, key):
        self._tsan(key, "read")
        return dict.__contains__(self, key)

    def __setitem__(self, key, value):
        self._tsan(key, "write")
        dict.__setitem__(self, key, value)

    def __delitem__(self, key):
        self._tsan(key, "write")
        dict.__delitem__(self, key)

    def pop(self, key, *default):
        self._tsan(key, "write")
        return dict.pop(self, key, *default)

    def setdefault(self, key, default=None):
        self._tsan(key, "write")
        return dict.setdefault(self, key, default)

    def update(self, *a, **kw):
        self._tsan(None, "write")
        dict.update(self, *a, **kw)

    def clear(self):
        self._tsan(None, "write")
        dict.clear(self)


_state_seq = {}   # display name -> instances registered so far


def _unique_state_name(name):
    """Per-instance state key: two objects registered under one display
    name must NOT share an access record — a fresh instance's
    initialization writes would land in the old record's shared epoch
    and read as races (the test-suite re-creates same-named metrics
    constantly)."""
    with _state_lock:
        n = _state_seq.get(name, 0) + 1
        _state_seq[name] = n
    return name if n == 1 else f"{name}#{n}"


def shared_dict(name, data=None):
    """A dict whose item accesses are race-checked under MXNET_TSAN=1;
    a plain dict otherwise (zero overhead)."""
    if not enabled():
        return dict(data or {})
    d = _SharedDict(data or {})
    d._tsan_state_name = _unique_state_name(name)
    return d


_instr_classes = {}   # original class -> instrumented subclass


def instrument(obj, name):
    """Register `obj` for attribute-write race tracking: every
    ``obj.attr = value`` from here on records (thread, locks held,
    file:line) and is checked against other threads' accesses.  Returns
    `obj` unchanged when the sanitizer is off, or when the class cannot
    be swapped (``__slots__`` layouts)."""
    if not enabled():
        return obj
    cls = type(obj)
    # __slots__ layouts have no instance dict to carry the state name
    # (and their attribute writes cannot be hooked per-instance): leave
    # the object untouched, as documented
    if getattr(obj, "__dict__", None) is None:
        return obj
    sub = _instr_classes.get(cls)
    if sub is None:
        def __setattr__(self, attr, value,
                        _base_set=cls.__setattr__):
            if not attr.startswith("_tsan"):
                sname = self.__dict__.get("_tsan_state_name")
                if sname is not None:
                    _access(sname, attr, "write")
            _base_set(self, attr, value)
        try:
            sub = type("_Tsan" + cls.__name__, (cls,),
                       {"__setattr__": __setattr__, "__slots__": ()})
        except TypeError:
            return obj
        _instr_classes[cls] = sub
    # name first, class swap second: a failed swap must leave a plain
    # object, never an instrumented class without its state name
    obj.__dict__["_tsan_state_name"] = _unique_state_name(name)
    try:
        obj.__class__ = sub
    except TypeError:
        del obj.__dict__["_tsan_state_name"]
        return obj
    return obj


# -- blocking calls under contended locks -------------------------------------

def note_blocking(kind, detail=""):
    """Report that the calling thread is about to block in `kind`
    (time.sleep / queue.get / socket.recv / device_get).  A finding
    fires when the thread holds an instrumented lock another thread
    also uses — everyone queued on that lock waits out this call too.
    Patched callables route here automatically; long-wait sites the
    patches cannot see (socket loops, device fetches) call it
    directly.  No-op when the sanitizer is off."""
    if not _installed and not enabled():
        return
    held = _held()
    if not held:
        return
    contended = [(info, site) for info, site in held if info.contended]
    if not contended:
        return
    info, lock_site = contended[-1]
    site = _site()
    _add_finding(
        "blocking", "blocking-under-lock", WARN,
        f"blocking {kind}({detail}) at {site} while holding contended "
        f"lock '{info.name}' (taken at {lock_site}): thread "
        f"'{threading.current_thread().name}' parks every thread queued "
        "on that lock behind this wait — move the blocking call outside "
        "the critical section",
        location=site, key=("blocking-under-lock", info.name, site))


# -- thread lifecycle ---------------------------------------------------------

def join_thread(thread, timeout, owner=None):
    """The audited close-path join: join with a timeout, and report a
    `thread-outlives-close` finding when the thread is still alive
    afterwards (its owner's close() returned with the worker running).
    A plain `thread.join(timeout)` when the sanitizer is off."""
    if thread is None:
        return True
    thread.join(timeout)
    alive = thread.is_alive()
    if alive and enabled():
        rec = _threads.get(thread) or {}
        born = rec.get("site", "<unknown>:0")
        _add_finding(
            "lifecycle", "thread-outlives-close", WARN,
            f"thread '{thread.name}' (started at {born}) is still alive "
            f"{timeout:g}s after "
            + (f"{owner}.close()" if owner else "its owner's close()")
            + " returned — the worker is wedged or the close path never "
              "signals it; it will outlive its owner and leak",
            location=_site(),
            key=("thread-outlives-close", thread.name, born))
    return not alive


def _lifecycle_findings():
    """Scan tracked threads for leaks (called from `findings()`)."""
    out = []
    with _state_lock:
        snapshot = list(_threads.items())
    for thread, rec in snapshot:
        alive = thread.is_alive()
        if not alive:
            if rec.get("joined") or thread.daemon:
                with _state_lock:
                    _threads.pop(thread, None)
            continue
        if thread is threading.current_thread() or thread.daemon:
            continue
        if not _ours(rec.get("site", "")):
            continue
        key = ("leaked-thread", thread.name, rec.get("site"))
        with _state_lock:
            if key in _findings:
                continue
        _add_finding(
            "lifecycle", "leaked-thread", WARN,
            f"non-daemon thread '{thread.name}' started at "
            f"{rec.get('site')} is still alive and was never joined — "
            "it will wedge interpreter shutdown; join it in the owner's "
            "close() (tsan.join_thread) or mark it a daemon",
            location=rec.get("site"), key=key)
    return out


# -- reporting ----------------------------------------------------------------

def findings():
    """Everything collected so far as a list of Findings (lock-order
    cycles first — they are the errors)."""
    _lifecycle_findings()
    with _state_lock:
        out = list(_findings.values())
    sev = {ERROR: 0, WARN: 1}
    out.sort(key=lambda f: sev.get(f.severity, 2))
    return out


def report():
    return Report(findings(), target="tsan")


def lock_graph():
    """The lock-acquisition-order graph: nodes (with the threads that
    used each lock) and first-seen ordered edges with both sites."""
    with _state_lock:
        return {
            "locks": [{"name": info.name,
                       "threads": sorted(info.threads),
                       "contended": info.contended}
                      for info in _lock_infos.values()],
            "edges": [dict(e) for e in _edges.values()],
        }


def dump(path=None):
    """Write findings + lock graph as one JSON artifact (the
    ``mxlint --tsan-report`` input).  Registered at atexit when
    ``MXNET_TSAN_LOG`` is set; each process appends ONE json line
    through the shared `obs.jsonl_sink` (O_APPEND line-atomic,
    pid/rank/thread-stamped), so the subprocesses of a chaos run share
    a log without clobbering each other's findings."""
    found = [f.as_dict() for f in findings()]
    with _state_lock:
        states = sorted({state for (state, _k) in _accesses})
    payload = {
        "pid": os.getpid(),
        "enabled": bool(_enabled),
        "findings": found,
        "lock_graph": lock_graph(),
        "tracked_shared_states": states,
    }
    if path is None:
        return payload
    from ..obs import jsonl_sink as _jsonl
    s = _jsonl.JsonlSink(path)
    s.write(payload)
    s.close()
    return payload


def reset():
    """Clear findings, the order graph, and access history (lock
    registrations survive — instances keep their identity)."""
    with _state_lock:
        _findings.clear()
        _edges.clear()
        _adj.clear()
        _accesses.clear()
        _threads.clear()
        # _state_seq is NOT cleared: instrumented objects that survive a
        # reset keep their unique keys, and a post-reset registration of
        # the same display name must not collide with them (the exact
        # false-positive class the per-instance suffix exists to stop)
        for info in _lock_infos.values():
            info.threads.clear()
            info.blocking_threads.clear()
