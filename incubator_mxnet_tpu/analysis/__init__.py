"""`mxlint` — pass-based static & trace analysis for TPU hazards.

Two front ends over one Finding/Report currency (findings.py):

* **graph passes** (graph_passes.py) — topo-ordered analyses over
  `Symbol` or saved symbol JSON: duplicate/empty names, dead outputs,
  aux-state races, f64 promotion, unbound inputs, TPU tile-alignment
  hints.  Reach them via `analysis.check(sym)`, `Module.check()`, or the
  `tools/mxlint.py` CLI.

* **trace passes** — runtime-adjacent, wired into the data plane:
  - donation.py: names the parameter whose buffer a donated fused step
    consumed when something reads it afterwards (replaces the opaque
    PJRT "Array has been deleted" death);
  - recompile.py: audits every new jit signature of the fused train
    programs and diagnoses shape churn (ragged final batches);
  - hostsync.py: attributes blocking `asnumpy`/`asscalar`/
    `wait_to_read` calls inside `Module.fit` / `Trainer.step` loops to
    the source line that asked for them;
  - source_lint.py: the same hazards found statically in a script's AST
    (the CLI's `.py` front end);
  - tsan.py + locks.py: the MXNET_TSAN=1 concurrency sanitizer — lock-
    order deadlock detection over the `analysis.locks` shims, lockset
    race attribution on registered shared state, blocking-call and
    thread-lifecycle audits (rendered by `mxlint --tsan-report`);
  - cost.py + budgets.py: mxcost — static per-program FLOPs/bytes/
    roofline against a device profile, dtype-flow defect chains
    (dequantize -> fp32 dot), collective enumeration via the shared
    kvstore bucket plan, liveness/peak-HBM + donation opportunities,
    hidden host-transfer detection; `mxlint --cost-report` gates the
    numbers against the committed COST_BUDGETS.json baseline.

Every finding code registers once in `findings.CODE_TABLE`
(code -> default severity -> one-line doc) — the stable `--json` key
contract.

Runtime passes activate with ``MXNET_ANALYSIS=1`` (or
`analysis.enable()`); collected findings are read via
`analysis.runtime_report()`.  Donation-error translation and
recompilation recording are always on — they cost nothing on the happy
path.
"""
from __future__ import annotations

__all__ = ["check", "check_json", "check_source", "check_source_file",
           "check_cost", "check_sharding", "enable", "disable",
           "enabled", "runtime_report", "reset_runtime", "Finding",
           "Report", "CODE_TABLE", "registered_codes"]

from .findings import (Finding, Report, ERROR, WARN, HINT,  # noqa: F401
                       CODE_TABLE, registered_codes)
from . import donation  # noqa: F401
from . import hostsync  # noqa: F401
from . import recompile  # noqa: F401

_enabled = None  # tri-state: None = read MXNET_ANALYSIS lazily


def enabled():
    """Whether the runtime trace passes are active."""
    global _enabled
    if _enabled is None:
        from .. import config as _config
        _enabled = bool(_config.get("MXNET_ANALYSIS"))
    return _enabled


def enable():
    """Turn the runtime trace passes on (programmatic MXNET_ANALYSIS=1)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def check(symbol, shapes=None, hints=True, target=None):
    """Run the static graph-pass catalog over a Symbol -> Report."""
    from . import graph_passes
    return graph_passes.check(symbol, shapes=shapes, hints=hints,
                              target=target)


def check_json(text, shapes=None, hints=True, target=None):
    """Analyze a saved symbol JSON string -> Report."""
    from . import graph_passes
    return graph_passes.check_json(text, shapes=shapes, hints=hints,
                                   target=target)


def check_source(text, filename="<string>"):
    """AST-lint python training-script source -> Report."""
    from . import source_lint
    return source_lint.scan_source(text, filename=filename)


def check_source_file(path):
    from . import source_lint
    return source_lint.scan_file(path)


def check_cost(symbol, shapes=None, dtypes=None, profile=None,
               target=None):
    """Run the mxcost static analyzer over a Symbol -> ProgramCost
    (its ``.report`` is an ordinary findings Report; see cost.py for
    the jaxpr/collective entry points)."""
    from . import cost
    return cost.analyze_symbol(symbol, shapes=shapes, dtypes=dtypes,
                               profile=profile, target=target)


def check_sharding(symbol, shapes=None, mesh="dp=8", rules=None,
                   dtypes=None, target=None):
    """Run the mxshard static SPMD sharding analyzer over a Symbol ->
    ShardReport (its ``.findings`` is an ordinary findings Report; see
    sharding.py for the collective-plan / budget / measured-cross-check
    entry points)."""
    from . import sharding
    return sharding.analyze_sharding(symbol, shapes=shapes, mesh=mesh,
                                     rules=rules, dtypes=dtypes,
                                     name=target)


def runtime_report():
    """Everything the runtime trace passes collected so far (host syncs
    in hot loops, recompilation churn, program-cache traffic, supervisor
    straggler/host-loss events) as one Report."""
    report = Report(target="runtime")
    report.extend(hostsync.findings())
    report.extend(recompile.findings())
    try:
        from .. import compile as _compile
        report.extend(_compile.findings())
    except Exception:
        pass
    try:
        from ..resilience import supervisor as _supervisor
        report.extend(_supervisor.findings())
    except Exception:
        pass
    try:
        from ..resilience import guardian as _guardian
        report.extend(_guardian.findings())
    except Exception:
        pass
    try:
        from .. import kvstore as _kvstore
        report.extend(_kvstore.findings())
    except Exception:
        pass
    try:
        from ..serving import fleet as _fleet
        report.extend(_fleet.findings())
    except Exception:
        pass
    from . import tsan as _tsan
    if _tsan.enabled():
        report.extend(_tsan.findings())
    return report


def reset_runtime():
    hostsync.reset()
    recompile.reset()
    try:
        from ..serving import fleet as _fleet
        _fleet.reset_findings()
    except Exception:
        pass
    try:
        from ..resilience import supervisor as _supervisor
        _supervisor.reset_findings()
    except Exception:
        pass
    try:
        from ..resilience import guardian as _guardian
        _guardian.reset_findings()
    except Exception:
        pass
    from . import tsan as _tsan
    _tsan.reset()
