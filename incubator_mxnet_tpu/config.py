"""Environment-variable configuration (reference `docs/faq/env_var.md`).

Every documented MXNET_* knob is registered here with its mapping onto
this framework.  Three honest statuses:

* honored    — changes behavior (the entry names the consumer)
* subsumed   — the mechanism it tuned does not exist on the XLA/TPU
  design (e.g. GPU memory pools, NNPACK, OpenMP tuning); reading it is
  harmless and a debug log records that it was ignored
* accepted   — parsed and exposed via `config.get`, consumers may adopt

`config.get(name, default)` is the single read path: values are parsed
to the registered type, and unknown MXNET_* variables in the process
environment produce one warning each (catching typos, the failure mode
env-knob systems actually have).
"""
from __future__ import annotations

import logging
import os

_LOG = logging.getLogger(__name__)

_BOOL = lambda s: s not in ("0", "false", "False", "")

# name -> (type, default, status, note)
KNOBS = {
    # -- engine / execution --------------------------------------------------
    "MXNET_ENGINE_TYPE": (str, "ThreadedEnginePerDevice", "honored",
                          "engine.py: NaiveEngine forces synchronous "
                          "dispatch (block_until_ready per op)"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": (_BOOL, True, "honored",
                                       "engine.bulk scopes batch host "
                                       "staging at inference"),
    "MXNET_EXEC_BULK_EXEC_TRAIN": (_BOOL, True, "honored",
                                   "engine.bulk scopes batch host staging "
                                   "in training"),
    "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN": (int, 15, "subsumed",
                                            "XLA fuses whole graphs; no "
                                            "segment cap applies"),
    "MXNET_EXEC_ENABLE_INPLACE": (_BOOL, True, "subsumed",
                                  "XLA buffer assignment handles aliasing"),
    "MXNET_EXEC_NUM_TEMP": (int, 1, "subsumed", "no temp-space workspace"),
    # -- threading -----------------------------------------------------------
    "MXNET_CPU_WORKER_NTHREADS": (int, 4, "honored",
                                  "default preprocess_threads for "
                                  "ImageRecordIter / DataLoader workers"),
    "MXNET_CPU_PRIORITY_NTHREADS": (int, 4, "subsumed", "no priority queue"),
    "MXNET_CPU_NNPACK_NTHREADS": (int, 4, "subsumed", "no NNPACK"),
    "MXNET_MP_WORKER_NTHREADS": (int, 1, "accepted", "dataloader workers"),
    "MXNET_OMP_MAX_THREADS": (int, 0, "honored",
                              "exported as OMP_NUM_THREADS for the native "
                              "IO library's OpenMP loops"),
    # -- gpu/memory knobs (no CUDA on this design) ---------------------------
    "MXNET_GPU_WORKER_NTHREADS": (int, 2, "subsumed", "no CUDA streams"),
    "MXNET_GPU_COPY_NTHREADS": (int, 2, "subsumed", "no CUDA copy engine"),
    "MXNET_GPU_MEM_POOL_RESERVE": (int, 5, "subsumed",
                                   "HBM is managed by PJRT; see "
                                   "storage.memory_stats()"),
    "MXNET_GPU_MEM_POOL_TYPE": (str, "Naive", "subsumed", "PJRT allocator"),
    "MXNET_GPU_MEM_POOL_ROUND_LINEAR_CUTOFF": (int, 24, "subsumed", ""),
    "MXNET_GPU_MEM_POOL_PAGE_SIZE": (int, 4096, "subsumed", ""),
    "MXNET_ENABLE_GPU_P2P": (_BOOL, True, "subsumed",
                             "ICI collectives are XLA-scheduled"),
    # -- kvstore / distributed ----------------------------------------------
    "MXNET_KVSTORE_REDUCTION_NTHREADS": (int, 4, "subsumed",
                                         "reduce is one XLA collective"),
    "MXNET_DECODE_SLOTS": (int, 8, "honored",
                           "KV-cache rows the continuous-batching "
                           "DecodeEngine advances per tick (the decode-"
                           "step program's fixed batch dimension)"),
    "MXNET_DECODE_BUCKETS": (str, "8,16,32", "honored",
                             "prompt-length bucket ladder for decode "
                             "prefill: one compiled signature per "
                             "bucket, prompts padded up"),
    "MXNET_DECODE_ADMIT_PER_TICK": (int, 2, "honored",
                                    "max sequences admitted (prefilled) "
                                    "per decode tick, so long prefill "
                                    "bursts never stall the running "
                                    "slots' decode step"),
    "MXNET_DECODE_MAX_NEW": (int, 32, "honored",
                             "default generation budget per sequence "
                             "when a request does not set "
                             "max_new_tokens"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (int, 1000000, "honored",
                                     "dist server round accounting "
                                     "threshold (dist/server.py)"),
    "MXNET_KVSTORE_USETREE": (_BOOL, False, "subsumed",
                              "topology is XLA's concern on the torus"),
    "MXNET_ENABLE_GPU_P2P_COMM": (_BOOL, True, "subsumed", ""),
    # -- io ------------------------------------------------------------------
    "MXNET_USE_NATIVE_IO": (_BOOL, True, "honored",
                            "native.py: disables the C++ IO library"),
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": (int, 1, "subsumed", "no cuDNN"),
    # -- model zoo / home ----------------------------------------------------
    "MXNET_HOME": (str, os.path.join(os.path.expanduser("~"), ".mxnet"),
                   "honored", "gluon model_zoo root directory"),
    # -- profiling / debug ---------------------------------------------------
    "MXNET_PROFILER_AUTOSTART": (_BOOL, False, "honored",
                                 "profiler.py starts a jax trace at import"),
    "MXNET_PROFILER_MODE": (int, 0, "accepted", ""),
    "MXNET_EXEC_VERBOSE_LOGGING": (_BOOL, False, "accepted", ""),
    "MXNET_SUBGRAPH_BACKEND": (str, "", "honored",
                               "symbol.simple_bind partitions with the "
                               "named subgraph property"),
    "MXNET_SUBGRAPH_VERBOSE": (_BOOL, True, "accepted", ""),
    "MXNET_SAFE_ACCUMULATION": (_BOOL, False, "honored",
                                "fp32 accumulation for low-precision "
                                "reductions (BatchNorm stats, optimizers "
                                "with multi_precision)"),
    # -- numerics ------------------------------------------------------------
    "MXNET_FORCE_F32_MATMUL": (_BOOL, False, "honored",
                               "sets jax default_matmul_precision=highest "
                               "(full-fp32 MXU inputs; this framework's "
                               "own knob)"),
    # -- TPU-framework-specific knobs ---------------------------------------
    "MXNET_FUSED_TRAIN_STEP": (_BOOL, True, "honored",
                               "Module.fit/Estimator.fit single-program "
                               "fused train step (fused.py)"),
    "MXNET_FUSED_STEP_BLOCK": (int, 8, "honored",
                               "K train steps per dispatch in Module.fit/"
                               "Estimator.fit: ONE lax.scan program runs K "
                               "stacked batches, amortizing host dispatch "
                               "(batch_end callbacks then fire in bursts "
                               "of K; set 1 to restore per-step dispatch)"),
    "MXNET_FUSED_BACKWARD": (_BOOL, True, "honored",
                             "eager loss.backward() as ONE jitted tape "
                             "replay per structure (autograd.py)"),
    "MXNET_FUSED_SCAN": (_BOOL, True, "honored",
                         "scan-over-layers graph dedup: runs of "
                         "structurally identical layer blocks lower to "
                         "ONE lax.scan body over per-layer params "
                         "stacked in-program (Symbol graphs via "
                         "analysis.scan_plan, Gluon HybridSequential "
                         "via identical-config children), shrinking "
                         "the graph XLA compiles while params/"
                         "checkpoints keep per-layer layout; "
                         "bit-identical to the inlined path"),
    "MXNET_FUSED_AUTODONATE": (_BOOL, True, "honored",
                               "donate per-step staged inputs whose "
                               "buffers provably die inside the fused "
                               "step (trace-time jaxpr liveness via "
                               "analysis.cost), letting XLA reuse them "
                               "for intermediates — peak-HBM relief; "
                               "staged inputs are re-owned first "
                               "(reown_for_donation discipline)"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (int, 1000000, "honored",
                                     "arrays with more elements flat-split "
                                     "into one range per server "
                                     "(dist kvstore key-range sharding)"),
    "MXNET_KVSTORE_COLLECTIVE": (_BOOL, True, "honored",
                                 "dist_sync gradients ride XLA collectives "
                                 "instead of the socket server"),
    "MXNET_KVSTORE_BUCKET_MB": (float, 32, "honored",
                                "gradient all-reduce bucket size cap on "
                                "kvstore='tpu'/'device': a batched push "
                                "packs keys into size-capped buckets "
                                "(priority order: last-produced grads "
                                "first) and dispatches each bucket's "
                                "collective asynchronously — O(buckets) "
                                "dispatches per step, overlapped with "
                                "host-side assembly"),
    "MXNET_KVSTORE_OVERLAP": (_BOOL, True, "honored",
                              "async per-bucket dispatch on the "
                              "collective kvstore (bucket k's all-reduce "
                              "executes while bucket k+1 assembles); 0 "
                              "blocks after each bucket — the A/B lever "
                              "tools/run_scaling.py benches"),
    "MXNET_MESH": (str, "", "honored",
                   "composed device-mesh spec for the fused train step, "
                   "e.g. 'dp=8' or 'dp=4,tp=2' (axis sizes multiply to "
                   "the device count; the dp axis shards the batch, "
                   "other axes are available to TP/PP-sharded params) — "
                   "the Module.fit/init_optimizer mesh= argument wins "
                   "over the env"),
    "MXNET_POD_SPMD": (_BOOL, True, "honored",
                       "pod SPMD fast path in the fused train step: the "
                       "whole step runs inside shard_map over the dp "
                       "axis and gradients exchange in O(buckets) "
                       "flatten-concat psum collectives "
                       "(MXNET_KVSTORE_BUCKET_MB caps a bucket) instead "
                       "of GSPMD's one all-reduce per tensor — fewer "
                       "cross-device barriers per step; falls back to "
                       "the global-view lowering for RNG/batch-"
                       "normalized/reduced-output graphs or composed "
                       "(tp/pp) meshes"),
    "MXNET_ZERO": (_BOOL, False, "honored",
                   "ZeRO-style weight-update sharding in the fused step: "
                   "optimizer-state tensors shard over the dp axis, so "
                   "XLA lowers the gradient exchange to reduce-scatter, "
                   "updates only the local shard, and all-gathers the "
                   "new weights (per-device optimizer memory 1/N)"),
    # -- mxcost static cost analysis (analysis/cost.py) ----------------------
    "MXNET_COST_PROFILE": (str, "tpu-v3", "honored",
                           "device profile the mxcost roofline "
                           "classifies against (analysis/cost.py "
                           "PROFILES: tpu-v3, tpu-v4, cpu-host)"),
    "MXNET_COST_DONATE_MIN_MB": (float, 1.0, "honored",
                                 "minimum buffer size for a donation-"
                                 "opportunity finding (step-boundary "
                                 "buffers that die undonated)"),
    "MXNET_SHARD_MIN_MB": (float, 1.0, "honored",
                           "mxshard (analysis/sharding.py) finding "
                           "floor: implicit-replication and "
                           "hidden-reshard fire only for tensors at "
                           "least this many MB"),
    # -- resilience (this framework's own knobs) -----------------------------
    "MXNET_FAULTS": (str, "", "honored",
                     "resilience/faults.py: deterministic fault-injection "
                     "spec, e.g. 'seed=7;transport.send:drop(at=3)'"),
    "MXNET_FAULTS_LOG": (str, "", "honored",
                         "append one JSON line per fired fault/retry event "
                         "(chaos-run artifacts; tools/run_chaos.py)"),
    "MXNET_PS_REQUEST_TIMEOUT": (float, 330.0, "honored",
                                 "dist transport per-request timeout; must "
                                 "exceed the server's 300s sync waits"),
    "MXNET_PS_CONNECT_WAIT": (float, 90.0, "honored",
                              "dist transport initial-connect window "
                              "(covers the worker/server startup race)"),
    "MXNET_PS_RECONNECT_WAIT": (float, 5.0, "honored",
                                "dist transport mid-request reconnect "
                                "window (failover diagnosis speed)"),
    "MXNET_PS_MAX_RETRIES": (int, 3, "honored",
                             "dist transport request attempts (backoff + "
                             "jitter; resends are idempotent via seq)"),
    "MXNET_PS_BREAKER_THRESHOLD": (int, 2, "honored",
                                   "consecutive exhausted-retry failures "
                                   "before a parameter server is declared "
                                   "lost (ServerLostError)"),
    "MXNET_PS_BREAKER_RESET_S": (float, 30.0, "honored",
                                 "open->half-open window of the per-server "
                                 "circuit breaker"),
    "MXNET_SERVING_BREAKER_THRESHOLD": (int, 5, "honored",
                                        "consecutive failed batches before "
                                        "a served model's breaker opens "
                                        "(fail fast, shed load)"),
    "MXNET_SERVING_BREAKER_RESET_S": (float, 30.0, "honored",
                                      "serving breaker open->half-open "
                                      "probe window"),
    # -- multi-replica serving router (serving/router.py) --------------------
    "MXNET_ROUTER_HEALTH_INTERVAL_S": (float, 0.5, "honored",
                                       "router health thread probe "
                                       "interval per replica (heartbeat; "
                                       "every k-th is a deepcheck)"),
    "MXNET_ROUTER_HEALTH_DEADLINE_S": (float, 5.0, "honored",
                                       "probe silence before a replica "
                                       "is declared dead and its "
                                       "in-flight requests fail over "
                                       "(a probe-failure BURST inside "
                                       "the deadline only suspends "
                                       "dispatch — no false eviction)"),
    "MXNET_ROUTER_DEEPCHECK_EVERY": (int, 8, "honored",
                                     "every Nth health probe runs a real "
                                     "bucket-1 inference through the "
                                     "compiled ladder instead of a cheap "
                                     "heartbeat (0 disables deepchecks)"),
    "MXNET_ROUTER_MAX_DISPATCHES": (int, 3, "honored",
                                    "dispatch attempts per request "
                                    "across replica deaths before the "
                                    "request fails (failover budget)"),
    "MXNET_ROUTER_SHED_BEST_EFFORT_MS": (float, 25.0, "honored",
                                         "estimated fleet wait beyond "
                                         "which best_effort requests "
                                         "are shed (the FIRST class to "
                                         "degrade under overload)"),
    "MXNET_ROUTER_SHED_BATCH_MS": (float, 100.0, "honored",
                                   "estimated fleet wait beyond which "
                                   "batch-class requests are shed"),
    "MXNET_ROUTER_SHED_INTERACTIVE_MS": (float, 1000.0, "honored",
                                         "estimated fleet wait beyond "
                                         "which even interactive "
                                         "requests are shed (the last "
                                         "line before queue collapse)"),
    # -- cross-host serving fleet (serving/fleet.py) -------------------------
    "MXNET_FLEET_TICK_S": (float, 0.5, "honored",
                           "FleetManager control-loop tick: the autoscaler "
                           "samples the router's est-wait signal and "
                           "reconciles the fleet to target once per tick"),
    "MXNET_FLEET_SLO_MS": (float, 100.0, "honored",
                           "the autoscaler's SLO on the admission "
                           "est-wait signal: sustained waits above it "
                           "scale the fleet up (the same queue-model "
                           "number the router sheds on)"),
    "MXNET_FLEET_UP_AFTER_S": (float, 3.0, "honored",
                               "est-wait must breach the SLO for this "
                               "long, uninterrupted, before a scale-up "
                               "(a transient burst never spawns)"),
    "MXNET_FLEET_DOWN_AFTER_S": (float, 30.0, "honored",
                                 "the fleet must be idle (est-wait under "
                                 "the idle threshold, nothing in flight) "
                                 "this long before a scale-down retires "
                                 "a replica through the drain path"),
    "MXNET_FLEET_IDLE_FRACTION": (float, 0.1, "honored",
                                  "idle threshold as a fraction of the "
                                  "SLO; est-wait between idle and SLO is "
                                  "the hysteresis dead band (both streaks "
                                  "reset, so a flapping signal can never "
                                  "thrash the fleet)"),
    "MXNET_FLEET_COOLDOWN_S": (float, 10.0, "honored",
                               "minimum spacing between scale events: "
                               "every action arms it, rate-limiting even "
                               "a pathological signal to one event per "
                               "window"),
    "MXNET_FLEET_MIN_REPLICAS": (int, 1, "honored",
                                 "scale-down floor (and the default "
                                 "initial target)"),
    "MXNET_FLEET_MAX_REPLICAS": (int, 8, "honored",
                                 "scale-up ceiling: breaches past it are "
                                 "counted (stats.signal.clamped_at_max), "
                                 "not acted on"),
    "MXNET_FLEET_HOST_HEARTBEAT_S": (float, 1.0, "honored",
                                     "interval of the fleet's host-agent "
                                     "heartbeats (fed into the "
                                     "dist.membership table)"),
    "MXNET_FLEET_HOST_DEADLINE_S": (float, 5.0, "honored",
                                    "heartbeat silence before a HOST is "
                                    "declared dead: all its replicas are "
                                    "marked dead at once, in-flight "
                                    "requests fail over, and the fleet "
                                    "backfills on surviving hosts"),
    # -- continuous train-to-serve loop (loop/) ------------------------------
    "MXNET_LOOP_PUBLISH_STEPS": (int, 100, "honored",
                                 "trained steps between registry "
                                 "publishes of the newest guardian-"
                                 "healthy checkpoint (0 disables the "
                                 "step cadence)"),
    "MXNET_LOOP_PUBLISH_SECS": (float, 0.0, "honored",
                                "wall-clock publish cadence in seconds "
                                "(0 disables; combines with the step "
                                "cadence — whichever fires first)"),
    "MXNET_LOOP_CANARY_TOL": (float, 0.02, "honored",
                              "canary gate tolerance: a candidate may "
                              "score up to this much BELOW the "
                              "incumbent on the pinned holdout and "
                              "still promote; anything worse is "
                              "rejected and stamped, never retried"),
    "MXNET_LOOP_POLL_S": (float, 2.0, "honored",
                          "LoopController registry poll interval"),
    "MXNET_LOOP_FRESHNESS_SLO_S": (float, 600.0, "honored",
                                   "freshness SLO: max acceptable "
                                   "loop.freshness_lag_s (data-seen "
                                   "watermark -> version live on the "
                                   "fleet), gated in LOOP_REPORT.json"),
    # -- training guardian (resilience/guardian.py) --------------------------
    "MXNET_GUARDIAN": (_BOOL, True, "honored",
                       "training health guardian in Module.fit: in-graph "
                       "all-finite + gradient-norm health word on the "
                       "fused step, skip-batch on non-finite updates, "
                       "rollback-to-last-good on loss spikes (with a "
                       "checkpoint_dir), bad-batch quarantine"),
    "MXNET_GUARDIAN_INTERVAL": (int, 8, "honored",
                                "trained steps between health-word "
                                "polls: the device scalars accumulate "
                                "and are gathered in ONE host read per "
                                "interval (no per-step host sync)"),
    "MXNET_GUARDIAN_SPIKE_WINDOW": (int, 16, "honored",
                                    "EWMA window (and warmup step "
                                    "count) of the loss-spike detector "
                                    "over the gradient-norm signal"),
    "MXNET_GUARDIAN_SPIKE_K": (float, 6.0, "honored",
                               "k-sigma divergence of the health "
                               "signal over its EWMA diagnosed as a "
                               "loss spike (rollback trigger)"),
    "MXNET_GUARDIAN_MAX_FAILURES": (int, 3, "honored",
                                    "consecutive unhealthy steps "
                                    "before the guardian escalates to "
                                    "TrainingDivergedError naming "
                                    "step, signal, and data shard"),
    "MXNET_GUARDIAN_MAX_ROLLBACKS": (int, 2, "honored",
                                     "rollback-to-last-good budget per "
                                     "fit; past it a spike escalates "
                                     "to TrainingDivergedError"),
    "MXNET_GUARDIAN_QUARANTINE": (str, "", "honored",
                                  "bad-data quarantine JSONL path "
                                  "(default: <checkpoint_dir>/"
                                  "quarantine.jsonl); quarantined "
                                  "positions/records are skipped on "
                                  "resume"),
    "MXNET_FIT_MAX_RESTARTS": (int, 2, "honored",
                               "Module.fit auto-restarts from the last "
                               "checkpoint after ServerLostError or "
                               "CollectiveTimeoutError at most this many "
                               "times"),
    # -- elastic multi-host supervisor (resilience/supervisor.py) -----------
    "MXNET_SUPERVISOR": (_BOOL, True, "honored",
                         "JobSupervisor around multi-worker Module.fit: "
                         "heartbeat/membership, hung-collective watchdog, "
                         "straggler detection, shrink-and-resume"),
    "MXNET_SUPERVISOR_HEARTBEAT_S": (float, 2.0, "honored",
                                     "heartbeat interval to the pod "
                                     "coordinator (the root parameter "
                                     "server)"),
    "MXNET_SUPERVISOR_DEADLINE_S": (float, 10.0, "honored",
                                    "heartbeat silence before a host is "
                                    "declared dead in the membership "
                                    "view"),
    "MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S": (float, 120.0, "honored",
                                              "watchdog deadline turning "
                                              "a hung cross-host "
                                              "collective into a "
                                              "CollectiveTimeoutError "
                                              "naming the absent hosts"),
    "MXNET_SUPERVISOR_STRAGGLER_K": (float, 3.0, "honored",
                                     "k-sigma divergence of a host's "
                                     "step-time EWMA from the pod median "
                                     "flagged as a straggler finding"),
    "MXNET_SUPERVISOR_SHRINK_BARRIER_S": (float, 30.0, "honored",
                                          "deadline of the epoch-fenced "
                                          "shrink barrier (survivors "
                                          "agreeing on the new world "
                                          "size)"),
    "MXNET_SUPERVISOR_EPOCH": (int, 0, "honored",
                               "membership epoch a (re)starting worker "
                               "registers at — set by the shrink-and-"
                               "resume path, not by hand; a stale epoch "
                               "is fenced out by the coordinator"),
    "MXNET_INTERNAL_CONV_LAYOUT": (str, "NCHW", "honored",
                                   "NHWC internal conv/pool/BN execution "
                                   "(ops/layout.py; measured ~parity on "
                                   "v5e, default off)"),
    "MXNET_FLASH_INTERPRET": (_BOOL, False, "honored",
                              "run the Pallas flash-attention kernel in "
                              "interpreter mode (CPU testing)"),
    "MXNET_FLASH_VMEM_MB": (float, 10.0, "honored",
                            "VMEM budget steering the whole-KV kernel vs "
                            "the KV-streaming grid (long-context) variant"),
    "MXNET_COMPILATION_CACHE_DIR": (str, "", "honored",
                                    "persistent XLA compilation cache "
                                    "directory (bench.py)"),
    # -- unified program cache (compile/) ------------------------------------
    "MXNET_PROGRAM_CACHE": (_BOOL, True, "honored",
                            "unified program cache (compile/): fused "
                            "train/inference/CachedOp programs share one "
                            "per-signature cache with AOT build + stats; "
                            "0 restores plain per-site jax.jit"),
    "MXNET_PROGRAM_CACHE_DIR": (str, "", "honored",
                                "persistent disk tier: XLA serialized "
                                "executables keyed by graph-hash x shapes "
                                "x dtypes x donation x device fingerprint "
                                "(CRC'd, atomic-rename entries); a second "
                                "process loads instead of recompiling"),
    "MXNET_PROGRAM_CACHE_LIMIT_MB": (int, 2048, "honored",
                                     "disk-tier size cap; stalest entries "
                                     "evicted (LRU by mtime) past it"),
    "MXNET_PROGRAM_CACHE_CHECKPOINT": (_BOOL, True, "honored",
                                       "ship a programs/ payload with "
                                       "elastic checkpoints so resumed "
                                       "jobs skip XLA compilation "
                                       "(checkpoint dir gains serialized "
                                       "executables; resume adds them as "
                                       "a cache source)"),
    "MXNET_ANALYSIS": (_BOOL, False, "honored",
                       "analysis/: runtime trace passes — per-parameter "
                       "donation tracking, host-sync attribution inside "
                       "Module.fit/Trainer.step, recompilation audit "
                       "(read with analysis.runtime_report())"),
    # -- concurrency sanitizer (analysis/tsan.py) ----------------------------
    "MXNET_TSAN": (_BOOL, False, "honored",
                   "analysis/tsan.py: runtime concurrency sanitizer — "
                   "locks built via analysis.locks feed a process-wide "
                   "lock-order graph (deadlock cycles reported before "
                   "they hang), registered shared state gets lockset "
                   "race attribution, blocking calls under contended "
                   "locks and leaked/unjoined threads are flagged; "
                   "unset, the lock shims ARE the plain threading "
                   "objects (zero overhead)"),
    "MXNET_TSAN_LOG": (str, "", "honored",
                       "write the sanitizer's findings + lock-order "
                       "graph as one JSON artifact at process exit "
                       "(rendered by tools/mxlint.py --tsan-report; "
                       "the run_tpu_parity tsan stage gates on it)"),
    "MXNET_TSAN_RAISE": (_BOOL, False, "honored",
                         "escalate a NEW lock-order deadlock cycle to "
                         "an MXNetError at the acquisition site instead "
                         "of only recording a finding (the lock is "
                         "released before raising)"),
    # -- production data plane (io_plane.py) ---------------------------------
    "MXNET_IO_RING": (_BOOL, True, "honored",
                      "h2d staging ring: Module.fit (and the gluon "
                      "Estimator) wrap the training iterator in a "
                      "DevicePrefetchIter — batches stage into reusable "
                      "host buffers, transfer on a dedicated mx-io-h2d "
                      "thread, and park in a device-resident prefetch "
                      "queue, so the train loop never blocks on "
                      "device_put; 0 restores the blocking path"),
    "MXNET_IO_PREFETCH": (int, 3, "honored",
                          "device-resident prefetch depth of the h2d "
                          "ring (bounded queue of already-transferred "
                          "batches; floor 2 — double buffering is the "
                          "minimum that overlaps transfer with compute)"),
    "MXNET_IO_STAGING": (_BOOL, True, "honored",
                         "assemble batches into reusable preallocated "
                         "host staging buffers before transfer (the "
                         "pinned-memory pattern: stable buffers, one "
                         "copy that also applies the dtype cast); 0 "
                         "transfers straight from the producer's arrays"),
    "MXNET_IO_UINT8_WIRE": (_BOOL, True, "honored",
                            "ImageRecordIter(device_augment='auto') "
                            "resolves to uint8-on-the-wire: the host "
                            "stops at crop+mirror and ships uint8 NHWC "
                            "(4x fewer h2d bytes than fp32), with "
                            "normalize/cast/layout fused into the step "
                            "program via normalize_symbol (explicit "
                            "device_augment=True/False always wins)"),
    "MXNET_IO_AUTO_SHARD": (_BOOL, True, "honored",
                            "an EXPLICIT num_parts='auto' on RecordIO-"
                            "backed iterators splits the record set by "
                            "this process's (rank, world) — DMLC_RANK/"
                            "DMLC_NUM_WORKER or the jax process grid — "
                            "re-resolved at every reset(), so "
                            "shrink-and-resume re-shards on the epoch "
                            "fence; 0 forces even 'auto' to a single "
                            "part (unset num_parts NEVER shards: eval "
                            "iterators must score the full set)"),
    # -- unified telemetry plane (obs/) --------------------------------------
    "MXNET_OBS_TRACE": (str, "", "honored",
                        "obs/trace.py: shared span JSONL file enabling "
                        "cross-process distributed tracing — every "
                        "process of a run (router, subprocess workers, "
                        "host daemons, parameter servers) appends its "
                        "finished spans there (O_APPEND line-atomic); "
                        "tools/mxtrace.py merges the file into ONE "
                        "Perfetto-loadable chrome trace with "
                        "cross-process flow arrows"),
    "MXNET_OBS_TRACE_BUFFER": (int, 65536, "honored",
                               "in-memory span buffer cap per process "
                               "(drop-oldest past it, counted in the "
                               "'trace.dropped' metric); spans "
                               "auto-flush to the shared file in "
                               "batches and at exit"),
    "MXNET_OBS_METRICS": (_BOOL, True, "honored",
                          "obs/metrics.py: invoke registered stats() "
                          "producers on scrape — off, collect() "
                          "returns raw instruments only (the paranoid "
                          "hot-path escape hatch; the 'metrics' "
                          "transport frame itself always answers)"),
    "MXNET_PROFILER_MAX_EVENTS": (int, 250000, "honored",
                                  "profiler.py in-memory custom-event "
                                  "buffer cap: a long supervised run "
                                  "with MXNET_PROFILER=1 drops the "
                                  "OLDEST events past it instead of "
                                  "exhausting host memory; drops are "
                                  "counted and surfaced as the "
                                  "'profiler.dropped_events' metric"),
    # -- sharded sparse embeddings (embedding/) ------------------------------
    "MXNET_EMBED_PARTITION": (str, "range", "honored",
                              "embedding/sharded.py row-partition rule: "
                              "'range' gives each shard one contiguous "
                              "row interval (reference ps-lite value "
                              "ranges), 'hash' spreads rows by a stable "
                              "integer mix of the row id (skew-resistant "
                              "for power-law id traffic)"),
    "MXNET_EMBED_CACHE_ROWS": (int, 4096, "honored",
                               "device-resident hot-row cache capacity "
                               "in rows per ShardedEmbedding (LRU over "
                               "row ids; 0 disables the cache and every "
                               "lookup pulls from its shard)"),
    "MXNET_EMBED_HBM_BUDGET_MB": (int, 64, "honored",
                                  "modeled single-device HBM budget for "
                                  "the embedding tier: ShardedEmbedding "
                                  "refuses to densify a table over it, "
                                  "and run_embed_bench certifies a "
                                  "table >= 4x this budget trains and "
                                  "serves sharded"),
    "MXNET_EMBED_PULL_CHUNK": (int, 65536, "honored",
                               "rows per embed_pull request when "
                               "streaming a whole shard back (checkpoint "
                               "capture / serving warm-up) so one reply "
                               "never materializes a table-sized frame"),
    "MXNET_EMBED_BREAKER_THRESHOLD": (int, 2, "honored",
                                      "consecutive exhausted-retry "
                                      "failures before an embedding "
                                      "shard is declared lost "
                                      "(ServerLostError naming the "
                                      "shard and its row range)"),
    "MXNET_EMBED_BREAKER_RESET_S": (float, 30.0, "honored",
                                    "open->half-open window of the "
                                    "per-shard embedding circuit "
                                    "breaker"),
}

_warned = set()


def get(name, default=None):
    """Read a knob with its registered parser; single read path."""
    if name not in KNOBS:
        raise KeyError(f"unknown config knob {name}; register it in "
                       "config.KNOBS")
    typ, reg_default, status, _ = KNOBS[name]
    raw = os.environ.get(name)
    if raw is None:
        return default if default is not None else reg_default
    if status == "subsumed" and name not in _warned:
        _warned.add(name)
        _LOG.debug("%s is set but subsumed by the XLA/TPU design; ignored",
                   name)
    try:
        return typ(raw)
    except (TypeError, ValueError):
        _LOG.warning("could not parse %s=%r; using default", name, raw)
        return default if default is not None else reg_default


def warn_unknown():
    """Flag MXNET_* env vars that match no registered knob (typo guard)."""
    unknown = []
    for key in os.environ:
        if key.startswith("MXNET_") and key not in KNOBS \
                and key not in _warned:
            _warned.add(key)
            unknown.append(key)
            _LOG.warning("environment variable %s matches no known knob "
                         "(typo? see config.KNOBS)", key)
    return unknown


def apply_startup_knobs():
    """Knobs that act at import time."""
    omp = get("MXNET_OMP_MAX_THREADS")
    if omp:
        os.environ.setdefault("OMP_NUM_THREADS", str(omp))
    if get("MXNET_FORCE_F32_MATMUL"):
        import jax
        jax.config.update("jax_default_matmul_precision", "highest")
    if get("MXNET_PROFILER_AUTOSTART"):
        from . import profiler
        try:
            profiler.set_state("run")
        except Exception:
            pass
    warn_unknown()
