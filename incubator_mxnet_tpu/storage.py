"""Storage management (reference `src/storage/storage.cc`,
`pooled_storage_manager.h`).

What remains of the reference's storage layer on this design, honestly:

* **Device (HBM) memory** is owned by PJRT — XLA's buffer assignment and
  the PJRT allocator replace `GPUPooledStorageManager` outright.  What
  the framework owes users is VISIBILITY, not another allocator:
  `memory_stats()` surfaces the PJRT per-device counters the reference
  exposed via `mx.context.gpu_memory_info`.
* **Host staging buffers** are the part still worth pooling: the input
  pipeline materializes one large float32 batch per step, and repeated
  malloc/free of tens-of-MB numpy buffers costs real time on the host.
  `HostStagingPool` recycles them by rounded size class, the same
  strategy as the reference's pooled manager
  (`pooled_storage_manager.h` round-to-bucket), applied where it still
  pays on TPU: between JPEG decode and `device_put`.
"""
from __future__ import annotations


import numpy as np

from .analysis import locks as _alocks

__all__ = ["HostStagingPool", "default_pool", "memory_stats",
           "device_memory_info"]


class HostStagingPool:
    """Size-class pool of host numpy buffers.

    acquire(shape, dtype) -> array backed by a pooled buffer;
    release(arr) returns the backing buffer.  Buffers round up to the
    next power-of-two byte size (the reference's bucket rounding), so a
    few classes serve all batch shapes.  Thread-safe; bounded.
    """

    def __init__(self, max_bytes=1 << 30):
        self._free = {}                 # rounded nbytes -> [np buffers]
        self._lock = _alocks.make_lock("storage.pool")
        self._max_bytes = max_bytes
        self._held = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _round(nbytes):
        return 1 << max(12, int(np.ceil(np.log2(max(1, nbytes)))))

    def acquire(self, shape, dtype=np.float32):
        dtype = np.dtype(dtype)
        need = int(np.prod(shape)) * dtype.itemsize
        size = self._round(need)
        with self._lock:
            bucket = self._free.get(size)
            if bucket:
                raw = bucket.pop()
                self._held -= size
                self.hits += 1
            else:
                raw = None
                self.misses += 1
        if raw is None:
            raw = np.empty(size, np.uint8)
        # the returned view keeps `raw` alive via .base; release() walks
        # the base chain back to the pooled buffer
        return raw[:need].view(dtype).reshape(shape)

    def release(self, arr):
        raw = arr
        while raw.base is not None:
            raw = raw.base
        if raw.dtype != np.uint8 or raw.ndim != 1:
            return False                # not one of ours
        size = raw.nbytes
        if size & (size - 1):
            return False
        with self._lock:
            if self._held + size > self._max_bytes:
                return False            # pool full: let gc take it
            bucket = self._free.setdefault(size, [])
            if any(r is raw for r in bucket):
                return False            # double release: keep one copy
            bucket.append(raw)
            self._held += size
        return True

    def stats(self):
        with self._lock:
            return {"held_bytes": self._held, "hits": self.hits,
                    "misses": self.misses,
                    "buckets": {k: len(v) for k, v in self._free.items()}}

    def clear(self):
        with self._lock:
            self._free.clear()
            self._held = 0


_default = None
_default_lock = _alocks.make_lock("storage.default")


def default_pool():
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HostStagingPool()
                # telemetry plane: the staging pool's hit economy
                # under the stable 'storage' namespace
                from .obs import metrics as _obs_metrics
                _obs_metrics.register_producer("storage", _default.stats)
    return _default


def memory_stats(ctx=None):
    """PJRT per-device memory counters (the `gpu_memory_info` role).

    Returns dict with at least bytes_in_use/peak_bytes_in_use when the
    backend reports them (TPU does; CPU returns {}).
    """
    from .context import current_context
    ctx = ctx or current_context()
    dev = ctx.jax_device
    try:
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def device_memory_info(ctx=None):
    """(free, total) bytes, reference `mx.context.gpu_memory_info`.
    (0, 0) when the backend reports no capacity figure."""
    stats = memory_stats(ctx)
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    if not total:
        return (0, 0)
    return (max(0, total - used), total)
