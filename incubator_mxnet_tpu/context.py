"""Device context abstraction.

Re-expression of the reference's `Context` (`include/mxnet/base.h:133-159`,
`python/mxnet/context.py`) for TPU: device types are {cpu, tpu} with `gpu`
kept as an alias for the accelerator so reference scripts written against
`mx.gpu()` run unmodified on TPU (`BASELINE.json` north star).  A Context maps
to a concrete `jax.Device`; NDArray buffers are committed to that device (HBM
via PJRT for tpu contexts).

When no accelerator platform is present (e.g. the CPU test mesh with
``--xla_force_host_platform_device_count=N``), `tpu(i)` resolves to host
device *i*, so cross-backend consistency tests in the reference's style
(`test_utils.check_consistency`) run anywhere.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """Device context (reference `python/mxnet/context.py:Context`).

    Parameters
    ----------
    device_type : {'cpu', 'tpu', 'gpu', 'cpu_pinned', 'cpu_shared'}
        'gpu' is accepted as an alias of 'tpu' (the accelerator).  The pinned /
        shared CPU types of the reference map to plain host memory under PJRT.
    device_id : int
    """

    # mirrors reference devtype2str / devstr2type tables
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    @property
    def is_accelerator(self):
        return self.device_type in ("gpu", "tpu")

    def __hash__(self):
        return hash((self.device_typeid if not self.is_accelerator else 2,
                     self.device_id))

    def __eq__(self, other):
        if not isinstance(other, Context):
            return False
        a = 2 if self.is_accelerator else self.device_typeid
        b = 2 if other.is_accelerator else other.device_typeid
        return a == b and self.device_id == other.device_id

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.stack.pop()

    # ---- JAX device resolution -------------------------------------------------
    @property
    def jax_device(self):
        """The concrete `jax.Device` backing this context."""
        return _resolve_device(self)

    def empty_cache(self):
        """Reference `Context.empty_cache` — PJRT owns pooling; no-op."""


def _accel_devices():
    import jax
    # LOCAL devices only: under jax.distributed, jax.devices() includes
    # other processes' (non-addressable) devices — a context must never
    # resolve to a device this process can't write
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    if devs:
        return devs
    return jax.local_devices()  # CPU fallback (virtual-device test mesh)


def _cpu_devices():
    import jax
    try:
        devs = [d for d in jax.local_devices() if d.platform == "cpu"]
        if devs:
            return devs
        # accelerator-only default platform (e.g. the axon TPU): the host
        # backend exists but is not among local_devices() — instantiate it
        # explicitly.  Without this, cpu() silently resolved to the TPU and
        # every "host" array (decoded batches, staging buffers) crossed the
        # interconnect/tunnel.
        return jax.devices("cpu")
    except RuntimeError:
        return jax.local_devices()


def _resolve_device(ctx):
    if ctx.is_accelerator:
        devs = _accel_devices()
    else:
        devs = _cpu_devices()
    return devs[ctx.device_id % len(devs)]


def cpu(device_id=0):
    """Host-memory context (reference `mx.cpu()`)."""
    return Context("cpu", device_id)


def tpu(device_id=0):
    """TPU context — the first-class accelerator (`BASELINE.json`: `mx.tpu()`)."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias for the accelerator so reference scripts run unmodified."""
    return Context("gpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def num_gpus():
    """Number of accelerator devices (reference `mx.context.num_gpus`)."""
    import jax
    return len([d for d in jax.devices() if d.platform != "cpu"])


def num_tpus():
    return num_gpus()


def current_context():
    """The default context (reference `python/mxnet/context.py:current_context`)."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return _default()


def _default():
    # TPU-first: if an accelerator is present, default remains cpu to match the
    # reference's semantics (mx.cpu() is the default ctx).
    return Context("cpu", 0)
