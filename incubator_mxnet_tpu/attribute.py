"""AttrScope (reference `python/mxnet/attribute.py`): a context manager
stamping attributes (ctx_group, lr_mult, ...) onto every symbol created
inside it — the legacy surface for model-parallel group placement:

    with mx.AttrScope(ctx_group="embed"):
        w = mx.sym.Variable("embed_weight")
    ...
    sym.simple_bind(ctx=mx.tpu(0), group2ctx={"embed": mx.cpu()}, ...)
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class AttrScope:
    def __init__(self, **attrs):
        self._attrs = {f"__{k}__" if not k.startswith("__") else k: str(v)
                       for k, v in attrs.items()}

    def get(self, user_attrs=None):
        merged = dict(self._attrs)
        if user_attrs:
            merged.update(user_attrs)
        return merged

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


def current_attrs():
    """Merged attrs of all active scopes (innermost wins)."""
    out = {}
    for scope in _stack():
        out.update(scope._attrs)
    return out
