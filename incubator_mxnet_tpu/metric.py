"""Evaluation metrics registry (reference `python/mxnet/metric.py`)."""
from __future__ import annotations

import math

import numpy

from .base import MXNetError
from .ndarray.ndarray import NDArray

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def alias(*aliases):
    def deco(klass):
        for a in aliases:
            _METRIC_REGISTRY[a.lower()] = klass
        return klass
    return deco


def create(metric, *args, **kwargs):
    """Reference `metric.py create`."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str) and metric.lower() in _METRIC_REGISTRY:
        return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)
    raise MXNetError(f"Metric must be callable/str/list, got {metric!r}")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name if name else numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def _as_numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    if len(labels) != len(preds):
        raise ValueError(f"Shape of labels {len(labels)} does not match shape "
                         f"of predictions {len(preds)}")
    return labels, preds


class EvalMetric:
    """Base metric (reference `metric.py:EvalMetric`)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    # -- device-side accumulation (fused train step) -------------------------
    # Metrics that can run in-graph define `device_update(labels, preds) ->
    # (sum_delta, num_delta)` over jax arrays; the fused Module train step
    # (`fused.FusedTrainStep`) then accumulates (sum, num) ON DEVICE as part
    # of the compiled program and stores the running totals here — `get()`
    # fetches them with a single host sync instead of one per batch.
    # Metrics without `device_update` keep the per-batch host path.
    _device_totals = None

    def _materialize(self):
        if self._device_totals is not None:
            import jax
            dsum, dnum = self._device_totals
            # ONE batched host read: on a remote device two sequential
            # float() fetches cost two round trips; device_get of the pair
            # costs one (the tunnel RTT dwarfs the 8 payload bytes)
            hsum, hnum = jax.device_get([dsum, dnum])
            self.sum_metric += float(hsum)
            self.num_inst += int(round(float(hnum)))
            self._device_totals = None

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._device_totals = None

    def get(self):
        self._materialize()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
@alias("composite")
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in metrics] if metrics else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            names.extend(name)
            values.extend(value)
        return names, values


@register
@alias("acc")
class Accuracy(EvalMetric):
    """Reference `metric.py:Accuracy`."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = _as_numpy(pred_label)
            if pred.ndim > 1 and pred.shape != _as_numpy(label).shape:
                pred = pred.argmax(axis=self.axis)
            lab = _as_numpy(label).astype("int32").reshape(-1)
            pred = pred.astype("int32").reshape(-1)
            self.sum_metric += (pred == lab).sum()
            self.num_inst += len(pred)

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            if pred.ndim > 1 and pred.shape != label.shape:
                pred = jnp.argmax(pred, axis=self.axis)
            lab = label.reshape(-1).astype(jnp.int32)
            pred = pred.reshape(-1).astype(jnp.int32)
            dsum = dsum + (pred == lab).sum()
            dnum = dnum + pred.size
        return dsum, dnum


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = numpy.argsort(_as_numpy(pred_label).astype("float32"))
            lab = _as_numpy(label).astype("int32")
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self.sum_metric += (pred.flat == lab.flat).sum()
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred[:, num_classes - 1 - j].flat == lab.flat).sum()
            self.num_inst += num_samples

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            if pred.ndim != 2:
                # raising at trace time makes the fused path fall back to
                # the host update, which surfaces the shape problem the
                # same way the reference does (silent skipping would
                # report NaN accuracy instead)
                raise ValueError(
                    f"TopKAccuracy expects 2-D predictions, got {pred.shape}")
            top_k = min(pred.shape[1], self.top_k)
            top = jnp.argsort(pred.astype(jnp.float32), axis=1)[:, -top_k:]
            lab = label.reshape(-1).astype(jnp.int32)
            dsum = dsum + (top == lab[:, None]).sum()
            dnum = dnum + pred.shape[0]
        return dsum, dnum


@register
class F1(EvalMetric):
    """Binary F1 (reference `metric.py:F1`)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.num_inst = self.metrics.total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


class _BinaryClassificationMetrics:
    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.true_positives = 0
        self.false_positives = 0
        self.true_negatives = 0
        self.false_negatives = 0

    def update_binary_stats(self, label, pred):
        pred = _as_numpy(pred)
        label = _as_numpy(label).astype("int32")
        pred_label = numpy.argmax(pred, axis=1) if pred.ndim > 1 else \
            (pred > 0.5).astype("int32")
        if len(numpy.unique(label)) > 2:
            raise ValueError("F1 currently only supports binary classification.")
        self.true_positives += ((pred_label == 1) & (label.reshape(-1) == 1)).sum()
        self.false_positives += ((pred_label == 1) & (label.reshape(-1) == 0)).sum()
        self.false_negatives += ((pred_label == 0) & (label.reshape(-1) == 1)).sum()
        self.true_negatives += ((pred_label == 0) & (label.reshape(-1) == 0)).sum()

    @property
    def precision(self):
        tp_fp = self.true_positives + self.false_positives
        return self.true_positives / tp_fp if tp_fp else 0.0

    @property
    def recall(self):
        tp_fn = self.true_positives + self.false_negatives
        return self.true_positives / tp_fn if tp_fn else 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (self.precision + self.recall)
        return 0.0

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives +
                self.true_negatives + self.true_positives)


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference `metric.py:MCC`)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        m = self._metrics
        terms = ((m.true_positives + m.false_positives) *
                 (m.true_positives + m.false_negatives) *
                 (m.true_negatives + m.false_positives) *
                 (m.true_negatives + m.false_negatives))
        denom = math.sqrt(terms) if terms else 1.0
        mcc = (m.true_positives * m.true_negatives -
               m.false_positives * m.false_negatives) / (denom or 1.0)
        if self._average == "macro":
            self.sum_metric += mcc
            self.num_inst += 1
            self._metrics.reset_stats()
        else:
            self.sum_metric = mcc * m.total_examples
            self.num_inst = m.total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        if hasattr(self, "_metrics"):
            self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    """Reference `metric.py:Perplexity`."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype("int32").reshape(-1)
            pred = _as_numpy(pred).reshape(-1, _as_numpy(pred).shape[-1]) \
                if _as_numpy(pred).ndim > 2 else _as_numpy(pred)
            probs = pred[numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = numpy.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= numpy.log(numpy.maximum(1e-10, probs)).sum()
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            lab = label.reshape(-1).astype(jnp.int32)
            pred = pred.astype(jnp.float32)
            if pred.ndim > 2:
                pred = pred.reshape(-1, pred.shape[-1])
            probs = jnp.take_along_axis(pred, lab[:, None], axis=1)[:, 0]
            if self.ignore_label is not None:
                ignore = lab == int(self.ignore_label)
                probs = jnp.where(ignore, 1.0, probs)
                dnum = dnum - ignore.sum()
            dsum = dsum - jnp.log(jnp.maximum(1e-10, probs)).sum()
            dnum = dnum + lab.shape[0]
        return dsum, dnum

    def get(self):
        self._materialize()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            label = label.reshape(label.shape[0], -1).astype(jnp.float32)
            pred = pred.reshape(pred.shape[0], -1).astype(jnp.float32)
            dsum = dsum + jnp.abs(label - pred).mean()
            dnum = dnum + 1
        return dsum, dnum


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            label = label.reshape(label.shape[0], -1).astype(jnp.float32)
            pred = pred.reshape(pred.shape[0], -1).astype(jnp.float32)
            dsum = dsum + ((label - pred) ** 2.0).mean()
            dnum = dnum + 1
        return dsum, dnum


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for label, pred in zip(labels, preds):
            lab = label.reshape(-1).astype(jnp.int32)
            pred = pred.astype(jnp.float32)
            prob = jnp.take_along_axis(pred, lab[:, None], axis=1)[:, 0]
            dsum = dsum + (-jnp.log(prob + self.eps)).sum()
            dnum = dnum + lab.shape[0]
        return dsum, dnum


@register
@alias("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples
            prob = pred[numpy.arange(num_examples), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += num_examples


@register
@alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self.sum_metric += numpy.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference `metric.py:Loss`)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_numpy(pred).sum()
            self.sum_metric += loss
            self.num_inst += _as_numpy(pred).size

    def device_update(self, labels, preds):
        import jax.numpy as jnp
        dsum, dnum = 0.0, 0.0
        for pred in preds:
            dsum = dsum + pred.astype(jnp.float32).sum()
            dnum = dnum + pred.size
        return dsum, dnum


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap a python feval(label, pred) (reference `metric.py:CustomMetric`)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1
