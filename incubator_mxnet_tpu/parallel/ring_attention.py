"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

The reference (2018-era) handles long sequences only by bucketing
(`BucketingModule`, SURVEY.md §5); this module provides the modern
first-class answer: each device holds a sequence shard of Q/K/V; K/V shards
rotate around the ring via `ppermute` while a blockwise online-softmax
accumulates exact attention — memory O(T/n) per device, ICI-bandwidth-bound.
(Technique: Liu et al., Ring Attention with Blockwise Transformers, 2023.)

`ring_attention` is written against named axes inside `shard_map`; it works
on any mesh axis (CPU test mesh included).  A Pallas-fused per-block kernel
can replace `_block_attn` later without changing the ring protocol.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, bias=None):
    """One (Tq, Tk) attention block returning (out_unnorm, row_max, row_sum).

    q: (B, Tq, H, D), k/v: (B, Tk, H, D)
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        scores = scores + bias
    m = jnp.max(scores, axis=-1)                      # (B, H, Tq)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)                           # (B, H, Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)           # (B, Tq, H, D)
    return o, m, l


def blockwise_attention(q, k, v, block_size=None, causal=False):
    """Single-device blockwise (memory-efficient) attention over KV blocks.
    Exact softmax via online accumulation (the flash-attention recurrence)."""
    B, T, H, D = q.shape
    bs = block_size or T
    nblocks = (k.shape[1] + bs - 1) // bs
    neg = jnp.asarray(-1e30, q.dtype)

    m = jnp.full((B, H, T), neg, q.dtype)
    l = jnp.zeros((B, H, T), q.dtype)
    o = jnp.zeros_like(q)

    q_pos = jnp.arange(T)
    for i in range(nblocks):
        ks = k[:, i * bs:(i + 1) * bs]
        vs = v[:, i * bs:(i + 1) * bs]
        bias = None
        if causal:
            k_pos = jnp.arange(i * bs, i * bs + ks.shape[1])
            mask = q_pos[:, None] >= k_pos[None, :]
            bias = jnp.where(mask, 0.0, neg)[None, None]
        bo, bm, bl = _block_attn(q, ks, vs, bias)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        o = o * alpha.transpose(0, 2, 1)[..., None] + \
            bo * beta.transpose(0, 2, 1)[..., None]
        m = m_new
    return o / l.transpose(0, 2, 1)[..., None]


def ring_attention(q, k, v, axis_name, causal=False, use_pallas=False):
    """Exact attention over sequence shards on `axis_name`.

    Call inside shard_map with q/k/v sharded on the sequence dim:
    q,k,v local shapes (B, T_local, H, D).  K/V rotate n-1 times around the
    ring; each step contributes one block to the online softmax.

    ``use_pallas=True`` computes each local block with the flash-attention
    Pallas kernel (`ops/flash_attention.py`) — O(T_local·D) VMEM streaming
    instead of a materialized (T_local, T_local) score block — while the
    ring protocol (ppermute + online-softmax merge) is unchanged.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    neg = jnp.asarray(-1e30, q.dtype)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        m, l, o, k_cur, v_cur = carry
        # which device's shard are we currently holding? source = my_idx - i
        src = (my_idx - i) % n
        if use_pallas:
            from ..ops.flash_attention import flash_attention_partial
            bo, bm, bl = flash_attention_partial(
                q, k_cur, v_cur, q_off=my_idx * Tl, k_off=src * Tl,
                causal=causal)
            bm = bm.astype(m.dtype)
            bl = bl.astype(l.dtype)
            bo = bo.astype(o.dtype)
        else:
            bias = None
            if causal:
                q_pos = my_idx * Tl + jnp.arange(Tl)
                k_pos = src * Tl + jnp.arange(Tl)
                mask = q_pos[:, None] >= k_pos[None, :]
                bias = jnp.where(mask, 0.0, neg)[None, None]
            bo, bm, bl = _block_attn(q, k_cur, v_cur, bias)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l2 = l * alpha + bl * beta
        o2 = o * alpha.transpose(0, 2, 1)[..., None] + \
            bo * beta.transpose(0, 2, 1)[..., None]
        # rotate KV to the next device; overlapped with next block's compute
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l2, o2, k_next, v_next), None

    m0 = jnp.full((B, H, Tl), neg, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    o0 = jnp.zeros_like(q)
    (m, l, o, _, _), _ = jax.lax.scan(step, (m0, l0, o0, k, v),
                                      jnp.arange(n))
    return o / l.transpose(0, 2, 1)[..., None]
