"""`mx.parallel` — first-class SPMD parallelism over the TPU device mesh.

This is the TPU-native generalization of the reference's distributed stack
(`src/kvstore/` NCCL/ps-lite, SURVEY.md §2.4): instead of push/pull servers
and reduction trees, training steps are jit-compiled SPMD programs over a
`jax.sharding.Mesh`, with XLA inserting ICI/DCN collectives:

* `mesh.py` — mesh construction (dp/tp/pp/sp axes) incl. multi-host
* `collectives.py` — named-axis collective wrappers (the NCCL verbs)
* `data_parallel.py` — shard_map data-parallel train step (kvstore 'tpu'
  semantics as one fused program)
* `tensor_parallel.py` — parameter-sharding rules (the model-parallel
  `group2ctx` answer, declarative)
* `ring_attention.py` — ring attention over the sp axis: blockwise softmax
  with ppermute'd KV shards (long-context support beyond the reference's
  bucketing strategy)
* `pipeline.py` — pipeline-parallel microbatch schedule over `pp`
"""
from .mesh import make_mesh, mesh_axes, local_mesh, rebuild
from .gluon_bridge import (shard_block, block_shardings,
                           shard_state_for_zero, put)
from .collectives import (all_reduce, all_gather, reduce_scatter, ppermute,
                          broadcast, supervised)
from .data_parallel import data_parallel_step, replicate, unreplicate
from .tensor_parallel import shard_params, ShardingRules
from .ring_attention import ring_attention, blockwise_attention
from .pipeline import pipeline_step, pipeline_train_step
from .zero import zero_train_step, zero_update, zero_init_state
