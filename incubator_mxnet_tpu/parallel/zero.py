"""ZeRO-style sharded optimizer state over the data axis.

The TPU mapping of the reference's *sharded parameter server*
(`src/kvstore/kvstore_dist_server.h:155` — each server owns a key range and
updates it; workers push grads, pull fresh weights): here every dp rank IS
one "server" owning 1/N of every parameter, the push is a
`psum_scatter` (reduce-scatter riding ICI), the server-side update runs on
the owned shard with 1/N-sized optimizer state, and the pull is an
`all_gather`.  This is ZeRO stage 1+2 (sharded states + sharded gradient
reduction); parameters stay replicated between steps.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["zero_init_state", "zero_update", "zero_train_step",
           "adam_shard_update", "sgd_shard_update"]


def _shard_size(size, n):
    return -(-size // n)  # ceil: shards are padded to equal size


def zero_init_state(params, n_shards, state_fn):
    """Global optimizer-state arrays for a ZeRO run.

    Every leaf's state is 1-D of global size n*ceil(size/n), sharded
    P(axis) so each rank materializes exactly its 1/N slice (lay it out
    with `jax.device_put` on a NamedSharding, or let `zero_train_step`'s
    in_spec place it).  state_fn(global_shape, dtype) -> state pytree for
    one leaf, e.g. lambda s, d: (jnp.zeros(s, d), jnp.zeros(s, d)) for
    (m, v).
    """
    def per_leaf(p):
        k = _shard_size(p.size, n_shards)
        return state_fn((n_shards * k,), p.dtype)
    return jax.tree_util.tree_map(per_leaf, params)


def zero_update(params, grads, state, update_fn, axis_name="dp"):
    """One sharded optimizer step inside shard_map.

    update_fn(p_shard, g_shard, s) -> (new_p_shard, new_s); all 1-D shards.
    grads are LOCAL per-rank gradients — the reduce-scatter here replaces
    the dp all-reduce, so callers must NOT pre-psum them.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    def per_leaf(p, g, s):
        size = p.size
        k = _shard_size(size, n)
        pad = k * n - size
        gflat = jnp.pad(g.reshape(-1), (0, pad))
        # mean-reduce-scatter: each rank receives the summed k-slice it owns
        gshard = jax.lax.psum_scatter(gflat.reshape(n, k), axis_name,
                                      scatter_dimension=0, tiled=False) / n
        pshard = jax.lax.dynamic_slice(jnp.pad(p.reshape(-1), (0, pad)),
                                       (idx * k,), (k,))
        new_pshard, new_s = update_fn(pshard, gshard, s)
        full = jax.lax.all_gather(new_pshard, axis_name, tiled=True)
        return full[:size].reshape(p.shape), new_s

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_leaves(grads)
    flat_s = treedef.flatten_up_to(state)
    new = [per_leaf(p, g, s) for p, g, s in zip(flat_p, flat_g, flat_s)]
    new_p = jax.tree_util.tree_unflatten(treedef, [a for a, _ in new])
    new_s = jax.tree_util.tree_unflatten(treedef, [b for _, b in new])
    return new_p, new_s


def zero_train_step(loss_fn, update_fn, mesh, axis_name="dp", donate=True):
    """Fused DP train step with ZeRO-sharded optimizer state.

    Like `data_parallel.data_parallel_step` but the gradient exchange is a
    reduce-scatter and the optimizer state lives sharded: per-device state
    memory is 1/N of the replicated version.

    Returns step(params, opt_state, batch) -> (params, opt_state, loss);
    params and batch as in the dp step; opt_state leaves are the local
    1/N shards (out_spec P(axis_name) on the leading dim).
    """
    from jax.sharding import PartitionSpec as P
    from .mesh import compat_shard_map

    def spmd_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, axis_name)
        new_params, new_state = zero_update(params, grads, opt_state,
                                            update_fn, axis_name)
        return new_params, new_state, loss

    step = compat_shard_map(
        spmd_step, mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P(axis_name), P()))
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def sgd_shard_update(momentum=0.9, lr=0.01, wd=0.0):
    def update(p, g, s):
        m = s[0] if isinstance(s, (tuple, list)) else s
        m2 = momentum * m - lr * (g + wd * p)
        return p + m2, (m2,) if isinstance(s, (tuple, list)) else m2
    return update


def adam_shard_update(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    """Adam on a parameter shard; state s = (m, v, t), t a (1,) step count."""
    def update(p, g, s):
        m, v, t = s
        t = t + 1
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * g * g
        mhat = m / (1 - beta1 ** t[0])
        vhat = v / (1 - beta2 ** t[0])
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), (m, v, t)
    return update