"""Named-axis collectives — the XLA verbs replacing NCCL
(reference `src/kvstore/kvstore_nccl.h:285-402` ncclReduce/ncclBcast and
`comm.h` reduce/broadcast).  These are thin wrappers so framework code reads
like the reference's comm layer while lowering to ICI collectives."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def all_reduce(x, axis_name, op="sum"):
    """ncclAllReduce equivalent."""
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    raise ValueError(f"unknown op {op}")


def all_gather(x, axis_name, axis=0, tiled=True):
    """ncclAllGather equivalent."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_axis=0):
    """ncclReduceScatter equivalent (ZeRO-style sharded grads)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                                tiled=True)


def ppermute(x, axis_name, perm):
    """Ring/neighbor exchange (the ring-reduce building block)."""
    return jax.lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, src=0):
    """ncclBcast equivalent: everyone takes src's value."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name):
    return jax.lax.psum(1, axis_name)


def supervised(name, fn, axis_name=None, timeout=None):
    """Dispatch a blocking HOST-LEVEL cross-host collective under the
    active `JobSupervisor`'s hung-collective watchdog (a plain call when
    none is active).  The in-graph verbs above run inside XLA programs
    where nothing can time them out — it is the host-side dispatch (the
    jitted call + `block_until_ready`) that a lost host hangs forever,
    and that is what gets the deadline:

        result = collectives.supervised(
            "grad-allreduce", lambda: allreduce_program(bucket),
            axis_name="dp")

    On expiry the watchdog raises `CollectiveTimeoutError` naming the
    collective, the axis, and the hosts that failed to arrive (from
    membership data).  mxlint's ``unsupervised-collective`` AST lint
    flags host-level collective dispatches that bypass this wrapper."""
    from ..resilience.supervisor import supervised as _supervised
    return _supervised(name, fn, axis=axis_name, timeout=timeout)
