"""Pipeline parallelism over the `pp` mesh axis.

Absent in the reference (closest: manual model-parallel LSTM layer placement,
`docs/faq/model_parallel_lstm.md`); provided here as a first-class GPipe-style
microbatch schedule: stages are one SPMD program where each pp rank applies
its stage function and passes activations to the next rank via ppermute,
with a steady-state loop over microbatches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def pipeline_step(stage_fn, n_microbatches, axis_name="pp"):
    """Build a pipelined forward over `axis_name`.

    stage_fn(params, x) -> y applies THIS rank's stage.  Input microbatches
    are fed on rank 0; outputs emerge on the last rank (gathered at the end).
    Returns fwd(params, microbatches) where microbatches has leading dim
    n_microbatches on every rank (only rank 0's values are used).
    """
    def fwd(params, microbatches):
        n_stages = jax.lax.psum(1, axis_name)
        my_idx = jax.lax.axis_index(axis_name)
        total_ticks = n_microbatches + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        mb_shape = microbatches.shape[1:]
        buf = jnp.zeros(mb_shape, microbatches.dtype)
        outputs = jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype)

        def tick(carry, t):
            buf, outputs = carry
            # rank 0 injects microbatch t (if in range); others use incoming
            inject = jnp.where(t < n_microbatches,
                               microbatches[jnp.minimum(t, n_microbatches - 1)],
                               jnp.zeros(mb_shape, microbatches.dtype))
            x = jnp.where(my_idx == 0, inject, buf)
            y = stage_fn(params, x)
            # last rank records its result for microbatch t - (n_stages - 1)
            out_t = t - (n_stages - 1)
            is_last = my_idx == n_stages - 1
            valid = jnp.logical_and(out_t >= 0, is_last)
            outputs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.maximum(out_t, 0)].set(y),
                lambda o: o,
                outputs)
            buf_next = jax.lax.ppermute(y, axis_name, perm)
            return (buf_next, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (buf, outputs),
                                       jnp.arange(total_ticks))
        # broadcast final outputs from last rank to all (so callers see them)
        outputs = jax.lax.psum(
            jnp.where(my_idx == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), axis_name)
        return outputs

    return fwd


def pipeline_train_step(stage_fn, loss_fn, n_microbatches, optimizer_update,
                        axis_name="pp", remat=True):
    """GPipe training over `axis_name`: forward all microbatches through the
    stage pipeline, one fused backward, per-stage parameter update.

    TPU-native design note: the forward schedule (`pipeline_step`) is an
    ordinary differentiable scan-of-ppermute program, so the reverse
    schedule — activations flowing backward through the inverse permutation,
    gradients accumulating per stage across microbatch ticks — is *derived
    by XLA* from the same program, instead of a hand-maintained backward
    pass (what `kvstore_dist_server.h`-era frameworks schedule by hand).
    `remat=True` rematerializes each stage in the backward pass (GPipe's
    activation checkpointing), trading FLOPs for HBM.

    stage_fn(stage_params, x) -> y            this rank's stage
    loss_fn(outputs, targets) -> scalar       computed on the (broadcast)
                                              pipeline outputs
    optimizer_update(p, g) -> new_p           per-leaf update

    Returns step(stage_params, microbatches, targets) -> (new_params, loss)
    to be wrapped in shard_map with params sharded over `axis_name` (leading
    stage dim) and microbatches/targets replicated or dp-sharded.
    """
    staged = jax.checkpoint(stage_fn) if remat else stage_fn
    fwd = pipeline_step(staged, n_microbatches, axis_name)

    def step(stage_params, microbatches, targets):
        def loss_of(p):
            out = fwd(p, microbatches)
            return loss_fn(out, targets)
        loss, grads = jax.value_and_grad(loss_of)(stage_params)
        # every rank evaluates the same replicated loss, and the transpose of
        # the output-broadcast psum sums all ranks' (identical) cotangents —
        # normalize so grads match the non-pipelined composition exactly
        n_stages = jax.lax.psum(1, axis_name)
        grads = jax.tree_util.tree_map(lambda g: g / n_stages, grads)
        new_params = jax.tree_util.tree_map(optimizer_update, stage_params,
                                            grads)
        return new_params, loss

    return step
