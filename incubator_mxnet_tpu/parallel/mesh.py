"""Device-mesh construction.

Replaces the reference's device topology machinery (`src/kvstore/
gpu_topology.h:491-782` PCIe/NVLink spanning trees): on TPU the physical
topology is the ICI torus and XLA's collective scheduler owns routing, so the
framework only chooses the *logical* mesh shape (dp/tp/pp/sp axes).
Multi-host: `jax.distributed.initialize` + `jax.devices()` spanning all hosts
gives a global mesh; DCN-vs-ICI placement follows axis order (outermost axes
land on DCN, reference scaling-book recipe).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

DEFAULT_AXES = ("dp", "tp")


def make_mesh(shape=None, axis_names=None, devices=None):
    """Create a `jax.sharding.Mesh`.

    shape: dict axis->size (e.g. {'dp': 4, 'tp': 2}) or tuple of sizes.
    Unspecified → all devices on one 'dp' axis.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        shape = {"dp": n}
    if isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        sizes = tuple(shape.values())
    else:
        sizes = tuple(shape)
        axis_names = tuple(axis_names or DEFAULT_AXES[:len(sizes)])
    total = int(np.prod(sizes))
    if total != n:
        raise MXNetError(f"mesh shape {sizes} needs {total} devices, "
                         f"have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names)


def local_mesh(n=None, axis_names=("dp",)):
    """Mesh over the first n local devices (testing convenience)."""
    import jax
    devs = jax.local_devices()
    n = n or len(devs)
    return make_mesh({axis_names[0]: n}, devices=devs[:n])


def mesh_axes(mesh):
    return tuple(mesh.axis_names)


def rebuild(axis_names=("dp",), per_host=None):
    """Rebuild the 1-axis data-parallel mesh over the CURRENT global
    device set — the shrink-and-resume step after a host loss: once the
    survivors have torn down and re-formed the process group
    (`dist.collective.shutdown()` + `init_process_group` at the smaller
    world size), `jax.devices()` spans only surviving hosts and every
    pre-shrink mesh is stale (it still holds the dead host's devices —
    dispatching on it hangs exactly like the collective being recovered
    from).  ``per_host`` optionally caps devices per process (testing
    convenience, mirrors `local_mesh`)."""
    import jax
    devices = jax.devices()
    if per_host is not None:
        by_proc = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        devices = [d for p in sorted(by_proc)
                   for d in by_proc[p][:int(per_host)]]
    return make_mesh({axis_names[0]: len(devices)}, devices=devices)


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host bring-up (replaces ps-lite scheduler bootstrapping,
    reference `tools/launch.py` + DMLC_PS_ROOT_URI env wiring)."""
    import jax
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
