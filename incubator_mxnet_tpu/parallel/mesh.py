"""Device-mesh construction.

Replaces the reference's device topology machinery (`src/kvstore/
gpu_topology.h:491-782` PCIe/NVLink spanning trees): on TPU the physical
topology is the ICI torus and XLA's collective scheduler owns routing, so the
framework only chooses the *logical* mesh shape (dp/tp/pp/sp axes).
Multi-host: `jax.distributed.initialize` + `jax.devices()` spanning all hosts
gives a global mesh; DCN-vs-ICI placement follows axis order (outermost axes
land on DCN, reference scaling-book recipe).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

DEFAULT_AXES = ("dp", "tp")


def make_mesh(shape=None, axis_names=None, devices=None):
    """Create a `jax.sharding.Mesh`.

    shape: dict axis->size (e.g. {'dp': 4, 'tp': 2}) or tuple of sizes.
    Unspecified → all devices on one 'dp' axis.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        shape = {"dp": n}
    if isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        sizes = tuple(shape.values())
    else:
        sizes = tuple(shape)
        axis_names = tuple(axis_names or DEFAULT_AXES[:len(sizes)])
    total = int(np.prod(sizes))
    if total != n:
        raise MXNetError(f"mesh shape {sizes} needs {total} devices, "
                         f"have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names)


# the accepted spec grammar, quoted by every parse error so a bad
# MXNET_MESH / Module.fit(mesh=) value is self-explaining
_SPEC_GRAMMAR = ("mesh spec grammar: comma-separated 'axis=size' "
                 "tokens, each axis a nonempty name and each size a "
                 "positive integer, e.g. 'dp=8' or 'dp=4,tp=2'")


def parse_spec(spec):
    """Parse a mesh spec string — ``'dp=8'``, ``'dp=4,tp=2'`` — into an
    ordered axis->size dict (the `MXNET_MESH` / ``Module.fit(mesh=)``
    currency).  Axis order is placement order: outermost axes land on
    DCN, innermost on ICI (scaling-book recipe).  A malformed spec
    raises `MXNetError` naming the offending token and the accepted
    grammar."""
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"bad token {part!r} in mesh spec {spec!r}: missing "
                f"'='; {_SPEC_GRAMMAR}")
        k, v = part.split("=", 1)
        k, v = k.strip(), v.strip()
        if not k:
            raise MXNetError(
                f"bad token {part!r} in mesh spec {spec!r}: empty axis "
                f"name; {_SPEC_GRAMMAR}")
        try:
            size = int(v)
        except ValueError:
            raise MXNetError(
                f"bad token {part!r} in mesh spec {spec!r}: size {v!r} "
                f"is not an integer; {_SPEC_GRAMMAR}")
        if size <= 0:
            raise MXNetError(
                f"bad token {part!r} in mesh spec {spec!r}: size must "
                f"be a positive integer; {_SPEC_GRAMMAR}")
        if k in out:
            raise MXNetError(
                f"bad token {part!r} in mesh spec {spec!r}: axis {k!r} "
                f"appears twice; {_SPEC_GRAMMAR}")
        out[k] = size
    return out


def mesh_from_spec(spec=None, devices=None):
    """Build a Mesh from a spec (string or axis->size dict); with
    ``spec=None`` reads `MXNET_MESH`.  Returns None when nothing is
    configured — callers fall back to their default 1-D dp mesh."""
    if spec is None or spec == "":
        from .. import config as _config
        spec = _config.get("MXNET_MESH")
    if not spec:
        return None
    if isinstance(spec, str):
        spec = parse_spec(spec)
    if not spec:
        return None
    return make_mesh(spec, devices=devices)


def dp_axis_of(mesh):
    """The data-parallel axis of a composed mesh: 'dp' when present,
    else the first axis (the convention every consumer shares)."""
    names = tuple(mesh.axis_names)
    return "dp" if "dp" in names else names[0]


def compat_shard_map(fn, mesh, in_specs, out_specs):
    """`shard_map` across the jax versions this framework supports: the
    stable `jax.shard_map` (check_vma) when present, else the
    `jax.experimental.shard_map` spelling (check_rep).  Every SPMD
    consumer (parallel/data_parallel.py, parallel/zero.py, the fused
    step's pod fast path) builds through this one seam."""
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=False)


def local_mesh(n=None, axis_names=("dp",)):
    """Mesh over the first n local devices (testing convenience)."""
    import jax
    devs = jax.local_devices()
    n = n or len(devs)
    return make_mesh({axis_names[0]: n}, devices=devs[:n])


def mesh_axes(mesh):
    return tuple(mesh.axis_names)


def rebuild(axis_names=("dp",), per_host=None):
    """Rebuild the 1-axis data-parallel mesh over the CURRENT global
    device set — the shrink-and-resume step after a host loss: once the
    survivors have torn down and re-formed the process group
    (`dist.collective.shutdown()` + `init_process_group` at the smaller
    world size), `jax.devices()` spans only surviving hosts and every
    pre-shrink mesh is stale (it still holds the dead host's devices —
    dispatching on it hangs exactly like the collective being recovered
    from).  ``per_host`` optionally caps devices per process (testing
    convenience, mirrors `local_mesh`)."""
    import jax
    devices = jax.devices()
    if per_host is not None:
        by_proc = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        devices = [d for p in sorted(by_proc)
                   for d in by_proc[p][:int(per_host)]]
    return make_mesh({axis_names[0]: len(devices)}, devices=devices)


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host bring-up (replaces ps-lite scheduler bootstrapping,
    reference `tools/launch.py` + DMLC_PS_ROOT_URI env wiring)."""
    import jax
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
