"""Data-parallel SPMD train step.

The TPU-native replacement for the reference's data-parallel machinery
(`DataParallelExecutorGroup` batch slicing + kvstore push/pull reduce,
`executor_group.py:281-310` + `comm.h`): ONE jit-compiled SPMD program per
step — forward, backward, gradient psum over the `dp` axis, and optimizer
update all fused by XLA, with the all-reduce riding the ICI mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def replicate(tree, mesh):
    """Place a pytree replicated over the whole mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def unreplicate(tree):
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, x.devices().pop())
                                  if hasattr(x, "devices") else x, tree)


def data_parallel_step(loss_fn, optimizer_update, mesh, axis_name="dp",
                      donate=True):
    """Build a fused DP train step.

    loss_fn(params, batch) -> scalar loss (per-shard mean)
    optimizer_update(params, grads, opt_state, lr) -> (new_params, new_opt_state)

    Returns step(params, opt_state, batch, lr) -> (params, opt_state, loss):
    params/opt_state replicated; batch sharded on axis 0 over `axis_name`.
    """
    from .mesh import compat_shard_map

    def spmd_step(params, opt_state, batch, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # gradient all-reduce over the data axis (kvstore push+pull fused)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, axis_name), grads)
        loss = jax.lax.pmean(loss, axis_name)
        new_params, new_opt = optimizer_update(params, grads, opt_state, lr)
        return new_params, new_opt, loss

    batch_spec = P(axis_name)
    rep = P()
    step = compat_shard_map(spmd_step, mesh=mesh,
                            in_specs=(rep, rep, batch_spec, rep),
                            out_specs=(rep, rep, rep))
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def sgd_tree_update(momentum=0.9, wd=0.0):
    """Simple fused SGD for pytrees (used by the dp step builder)."""
    def update(params, grads, opt_state, lr):
        def upd(p, g, m):
            m2 = momentum * m - lr * (g + wd * p)
            return p + m2, m2
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(opt_state)
        new = [upd(p, g, m) for p, g, m in zip(flat_p, flat_g, flat_m)]
        new_p = jax.tree_util.tree_unflatten(treedef, [a for a, _ in new])
        new_m = jax.tree_util.tree_unflatten(treedef, [b for _, b in new])
        return new_p, new_m
    return update
