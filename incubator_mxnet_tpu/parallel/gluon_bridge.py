"""Mesh parallelism through the Gluon front-end.

The reference's model parallelism asks users to pin layers to devices by
hand (`ctx_group` attrs + `group2ctx`, `symbol.py:1336-1439`); its data
parallelism copies parameters per device.  On TPU both collapse into
sharding annotations: parameters live as ONE global array laid out over
the `jax.sharding.Mesh`, eager and hybridized compute propagates the
shardings, and XLA/GSPMD inserts every collective (the all-gathers and
partial-sum reductions the reference's `_CrossDeviceCopy` op and NCCL
reduce did by hand).

Usage::

    mesh = mx.parallel.make_mesh(tp=2, dp=4)
    net.initialize(ctx=mx.cpu())           # single global copy
    mx.parallel.shard_block(net, mesh, ShardingRules.megatron("tp"))
    trainer = gluon.Trainer(net.collect_params(), "adam", ...,
                            zero=mesh)     # ZeRO: optimizer state sharded

Training then proceeds with the ordinary autograd/Trainer loop; tensor
parallelism, the data-parallel gradient reduction, and ZeRO state
sharding all happen inside the compiled steps.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .tensor_parallel import ShardingRules

__all__ = ["shard_block", "block_shardings", "shard_state_for_zero", "put"]


def put(x, mesh, spec=P()):
    """Place an NDArray (or raw array) on the mesh with `spec` — e.g.
    ``put(batch, mesh, P("dp"))`` shards the batch dim for data
    parallelism, the input-side counterpart of `shard_block`."""
    from ..ndarray.ndarray import NDArray
    sharding = NamedSharding(mesh, spec)
    if isinstance(x, NDArray):
        x._set_data(jax.device_put(x._data, sharding))
        return x
    return jax.device_put(x, sharding)


def _clean_spec(shape, spec, mesh):
    """Drop sharded axes that do not divide the dimension."""
    ext = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    clean = []
    for dim, ax in zip(shape, ext):
        if ax is None:
            clean.append(None)
        else:
            size = mesh.shape[ax] if isinstance(ax, str) else 1
            clean.append(ax if size and dim % size == 0 else None)
    return P(*clean)


def block_shardings(block, mesh, rules=None):
    """{param name: NamedSharding} for every parameter of `block`."""
    rules = rules or ShardingRules()
    out = {}
    for p in block.collect_params().values():
        spec = _clean_spec(p.shape, rules.spec_for(p.name), mesh)
        out[p.name] = NamedSharding(mesh, spec)
    return out


def shard_block(block, mesh, rules=None):
    """Lay every initialized parameter (and its gradient buffer) of
    `block` out over `mesh` per `rules`.

    Parameters must be initialized on a SINGLE context (one global copy);
    after this call each parameter's array is mesh-sharded and all
    subsequent forward/backward/update compute follows the layout.
    Returns the {name: NamedSharding} map applied.
    """
    shardings = block_shardings(block, mesh, rules)
    for p in block.collect_params().values():
        datas = p._data
        if datas is None:
            raise ValueError(
                f"Parameter {p.name} is not initialized; call "
                "initialize(ctx=<one ctx>) before shard_block")
        if len(datas) != 1:
            raise ValueError(
                f"Parameter {p.name} is replicated over {len(datas)} "
                "contexts; mesh sharding needs a single global copy "
                "(initialize with one ctx)")
        s = shardings[p.name]
        datas[0]._set_data(jax.device_put(datas[0]._data, s))
        if p._grad:
            for g in p._grad:
                g._set_data(jax.device_put(g._data, s))
    return shardings


def shard_state_for_zero(state, mesh, axis):
    """Shard optimizer-state NDArrays over `axis` (ZeRO: each rank holds
    1/N of every state tensor; XLA partitions the update elementwise and
    all-gathers the fresh weights because the weights stay replicated —
    the TPU reading of the reference's range-sharded parameter servers,
    `kvstore_dist_server.h`).  Leaves whose leading dim doesn't divide the
    axis stay replicated."""
    from ..ndarray.ndarray import NDArray

    n = mesh.shape[axis]

    def place(leaf):
        if leaf is None or not isinstance(leaf, NDArray):
            return
        if leaf.ndim and leaf.shape[0] % n == 0:
            spec = P(axis)
        else:
            spec = P()
        leaf._set_data(jax.device_put(leaf._data, NamedSharding(mesh, spec)))

    if isinstance(state, NDArray) or state is None:
        place(state)
    else:
        for leaf in jax.tree_util.tree_leaves(
                state, is_leaf=lambda x: isinstance(x, NDArray)):
            place(leaf)
