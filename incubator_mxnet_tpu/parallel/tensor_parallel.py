"""Tensor-parallel sharding rules.

The declarative successor to the reference's manual model parallelism
(`ctx_group` attrs + `group2ctx` bind arg, `symbol.py:1336-1439`, PlaceDevice
pass): parameters get `PartitionSpec`s by name pattern; XLA/GSPMD inserts the
all-gathers/reduce-scatters that the reference's `_CrossDeviceCopy` op did by
hand.  Megatron-style rules: column-parallel then row-parallel pairs."""
from __future__ import annotations

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules applied to parameter names."""

    def __init__(self, rules=(), default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, name):
        for prog, spec in self.rules:
            if prog.search(name):
                return spec
        return self.default

    @staticmethod
    def megatron(tp_axis="tp"):
        """Column-parallel qkv/ffn-in, row-parallel proj/ffn-out."""
        return ShardingRules([
            (r"(qkv|query|key|value|gate|up|fc1|ffn_in).*weight", P(tp_axis, None)),
            (r"(out_proj|down|fc2|ffn_out|proj).*weight", P(None, tp_axis)),
            (r"embed.*weight", P(tp_axis, None)),
            (r"bias", P()),
        ])


def shard_params(params, mesh, rules, name_fn=None):
    """Place a dict/pytree of params per the rules.

    params: dict name -> array (or pytree with string paths via name_fn).
    """
    out = {}
    for name, arr in params.items():
        spec = rules.spec_for(name if name_fn is None else name_fn(name))
        # drop axes that don't divide
        clean = []
        for dim, ax in zip(arr.shape, tuple(spec) + (None,) * (arr.ndim - len(spec))):
            if ax is None:
                clean.append(None)
            else:
                size = mesh.shape[ax] if isinstance(ax, str) else 1
                clean.append(ax if dim % size == 0 else None)
        sharding = NamedSharding(mesh, P(*clean))
        data = arr._data if hasattr(arr, "_data") else arr
        out[name] = jax.device_put(data, sharding)
    return out


def group2ctx_shardings(symbol, group2axis, mesh):
    """Bridge legacy `group2ctx` model parallelism to mesh shardings.

    The reference pins each ctx_group's parameters to a device
    (`executor.py group2ctx`); the TPU-native equivalent shards or pins
    them over mesh axes.  group2axis maps group name -> PartitionSpec
    (or axis name, sharded on dim 0).  Returns {var_name: NamedSharding}
    for every __ctx_group__-annotated variable, ready for
    `jax.device_put` / `jit(in_shardings=...)`.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = {}
    for node in symbol._topo():
        if not node.is_variable:
            continue
        g = node._extra_attrs.get("__ctx_group__")
        if g is None or g not in group2axis:
            continue
        spec = group2axis[g]
        if isinstance(spec, str):
            spec = P(spec)
        out[node.name] = NamedSharding(mesh, spec)
    return out
