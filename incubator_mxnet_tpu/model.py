"""Training glue + checkpoint conventions (reference `python/mxnet/model.py`)."""
from __future__ import annotations

import logging
from collections import namedtuple

from .base import MXNetError
from . import kvstore as kvs
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import symbol as sym

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Reference `model.py:67-114 _create_kvstore`."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(nd_arr.size)
                               for nd_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Reference `model.py _initialize_kvstore`."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Reference `model.py:145`: push grads, pull updated weights.

    Stores that prefer batching (collective data plane) get the FULL key
    list in one push/pull pair so the step costs ~one fused all-reduce
    instead of one collective per parameter (reference batched NCCL push,
    `model.py:125`)."""
    if getattr(kvstore, "prefers_batched_push", False):
        idxs = [i for i, g in enumerate(grad_arrays) if g[0] is not None]
        if idxs:
            names = [param_names[i] for i in idxs]
            kvstore.push(names, [grad_arrays[i] for i in idxs])
            kvstore.pull(names, [param_arrays[i] for i in idxs])
        return
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Reference `model.py _update_params`: reduce via kvstore (optional),
    update locally per device."""
    updates = [[] for _ in range(num_device)]
    batched = kvstore is not None and getattr(
        kvstore, "prefers_batched_push", False)
    if batched:
        # one bucketed reduce for the whole key list up front (reference
        # batched NCCL push, `model.py:125`); the per-param loop below
        # then only accumulates the local updates
        idxs = [i for i, g in enumerate(grad_arrays) if g[0] is not None]
        if idxs:
            names = [param_names[i] for i in idxs]
            kvstore.push(names, [grad_arrays[i] for i in idxs])
            kvstore.pull(names, [grad_arrays[i] for i in idxs])
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore and not batched:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            updater(*upd)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Reference `model.py:383 save_checkpoint`: prefix-symbol.json +
    prefix-%04d.params.

    Kept as the thin reference-compatible wrapper (synchronous, whole
    model, params only — the byte format interchanges with reference
    MXNet); production fault tolerance lives in the `checkpoint` package
    (async snapshots, atomic manifests, full training state, auto-resume).
    Both files here are still committed via temp-file + ``os.replace`` so
    even this legacy path never leaves a torn checkpoint behind.
    """
    import os

    def _atomic(path, write):
        tmp = "%s.tmp.%d" % (path, os.getpid())
        try:
            write(tmp)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    if symbol is not None:
        _atomic(f"{prefix}-symbol.json", symbol.save)
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    _atomic(param_name, lambda tmp: nd.save(tmp, save_dict))
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Reference `model.py:413 load_checkpoint`."""
    symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy training API (reference `model.py:451 FeedForward`) — kept for
    scripts predating Module; internally an adapter over `mod.Module`,
    which owns the jit-compiled executor group."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod
        from .context import cpu
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [cpu()]
        if not isinstance(self.ctx, (list, tuple)):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # -- internals -------------------------------------------------------------
    def _label_names(self):
        # classic convention: the symbol's label argument(s) end in
        # "_label" (reference model.py label handling)
        return [n for n in self.symbol.list_arguments()
                if n.endswith("_label")]

    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter
        if isinstance(X, DataIter):
            return X
        labels = self._label_names()
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                           shuffle=shuffle,
                           label_name=labels[0] if labels
                           else "softmax_label")

    def _build_module(self, data_iter):
        from .module import Module
        data_names = [d.name for d in data_iter.provide_data]
        self._module = Module(self.symbol, data_names=tuple(data_names),
                              label_names=tuple(self._label_names()),
                              context=self.ctx)
        return self._module

    # -- API -------------------------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        train = self._as_iter(X, y, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._as_iter(eval_data[0], eval_data[1])
        mod = self._build_module(train)
        opt_params = dict(self.kwargs)
        mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=opt_params,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=self.arg_params is not None,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        import numpy as _np
        data = self._as_iter(X)
        if reset:
            data.reset()
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data, for_training=False)
            # allow_missing: a loss symbol's label variable has no param
            # entry at inference (predict mode ignores it)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=True)
        outs = []
        for i, batch in enumerate(data):
            if num_batch is not None and i >= num_batch:
                break
            self._module.forward(batch, is_train=False)
            out = self._module.get_outputs()[0].asnumpy()
            if batch.pad:
                out = out[: out.shape[0] - batch.pad]
            outs.append(out)
        return _np.concatenate(outs, axis=0)

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        from . import metric as metric_mod
        data = self._as_iter(X)
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=True)
        metric = metric_mod.create(eval_metric)
        res = self._module.score(data, metric, num_batch=num_batch)
        return dict(res).popitem()[1]

    def save(self, prefix, epoch=None):
        epoch = epoch if epoch is not None else (self.num_epoch or 0)
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, **kwargs):
        """Reference `model.py create`: construct AND fit."""
        fit_kwargs = {k: kwargs.pop(k) for k in
                      ("eval_data", "eval_metric", "epoch_end_callback",
                       "batch_end_callback", "kvstore", "logger")
                      if k in kwargs}
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch, **kwargs)
        model.fit(X, y, **fit_kwargs)
        return model
