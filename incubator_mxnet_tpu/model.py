"""Training glue + checkpoint conventions (reference `python/mxnet/model.py`)."""
from __future__ import annotations

import logging
from collections import namedtuple

from .base import MXNetError
from . import kvstore as kvs
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import symbol as sym

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Reference `model.py:67-114 _create_kvstore`."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(nd_arr.size)
                               for nd_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Reference `model.py _initialize_kvstore`."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Reference `model.py:145`: push grads, pull updated weights."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Reference `model.py _update_params`: reduce via kvstore (optional),
    update locally per device."""
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            updater(*upd)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Reference `model.py:383 save_checkpoint`: prefix-symbol.json +
    prefix-%04d.params."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Reference `model.py:413 load_checkpoint`."""
    symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)
