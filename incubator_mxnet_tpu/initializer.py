"""Weight initializers (reference `python/mxnet/initializer.py`)."""
from __future__ import annotations

import json
import math
import re

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from . import random as _random

_INIT_REGISTRY = {}


class InitDesc(str):
    """Name + attrs descriptor (reference `initializer.py InitDesc`)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


def register(klass):
    name = klass.__name__.lower()
    _INIT_REGISTRY[name] = klass
    # reference registers plural aliases for Zero/One
    if name == "zero":
        _INIT_REGISTRY["zeros"] = klass
    if name == "one":
        _INIT_REGISTRY["ones"] = klass
    return klass


class Initializer:
    """Base initializer; callable on (InitDesc, NDArray)
    (reference `initializer.py:Initializer`)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be string or InitDesc")
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(name, arr)
        elif name.endswith("parameters"):
            # FusedRNNCell's flat 1-D parameter block: honor the concrete
            # initializer when it can handle a vector (Zero/Constant/
            # Uniform); fan-in schemes like Xavier cannot, so fall back to
            # small uniform (the reference's FusedRNN default)
            try:
                self._init_weight(name, arr)
            except Exception:
                self._set(arr, _random.host_rng().uniform(-0.07, 0.07, arr.shape))
        else:
            self._init_default(name, arr)

    def _set(self, arr, np_values):
        from . import engine as _engine
        vals = np.asarray(np_values).astype(np.dtype(arr.dtype), copy=False)
        if _engine.bulk_active():
            # host-stage; the engine flush batches the device transfer
            arr._data = vals
            _engine.stage(arr)
            return
        import jax.numpy as jnp
        arr._data = jnp.asarray(vals)

    def _init_zero(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_gamma(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_beta(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, arr):
        raise ValueError(
            f"Unknown initialization pattern for {name}. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" and \"beta\". "
            "Please use mx.sym.Variable(init=mx.init.*) to set initialization "
            "pattern")


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, np.zeros(arr.shape))


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, np.ones(arr.shape))


@register
class Constant(Initializer):
    def __init__(self, value=0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        if isinstance(self.value, NDArray):
            self._set(arr, self.value.asnumpy())
        else:
            self._set(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, _random.host_rng().uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, _random.host_rng().normal(0, self.sigma, arr.shape))


@register
class Xavier(Initializer):
    """Reference `initializer.py Xavier` (:728 area)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(f"Xavier initializer cannot be applied to vector "
                             f"{name}. It requires at least 2D.")
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _random.host_rng().uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, _random.host_rng().normal(0, scale, shape))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _random.host_rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _random.host_rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * res).reshape(arr.shape))


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference initializer.py Bilinear)."""

    def _init_weight(self, _, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i,f,g,o gate order
        self._set(arr, b)

    def _init_bias(self, name, arr):
        self._init_weight(name, arr)


class Load:
    """Init from saved dict, falling back to default_init
    (reference initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if src.shape != arr.shape:
                raise ValueError(f"Parameter {name} cannot be initialized from "
                                 "loading. Shape mismatch, "
                                 f"target {arr.shape} vs loaded {src.shape}")
            arr._data = src._data.astype(arr.dtype)
        else:
            if self.default_init is None:
                raise ValueError(f"Cannot Initialize {name}. Not found in "
                                 "loaded param and no default Initializer is "
                                 "provided.")
            self.default_init(name, arr)


class Mixed:
    """Pattern-dispatched initializers (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers mismatch")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"Parameter name {name} did not match any pattern")


def create(init, **kwargs):
    if isinstance(init, Initializer):
        return init
    if callable(init):
        return init
    if isinstance(init, str):
        if init.startswith("["):
            name, args = json.loads(init)
            return _INIT_REGISTRY[name.lower()](**args)
        if init.lower() not in _INIT_REGISTRY:
            raise MXNetError(f"Unknown initializer {init}")
        return _INIT_REGISTRY[init.lower()](**kwargs)
    raise MXNetError(f"Cannot create initializer from {init!r}")
