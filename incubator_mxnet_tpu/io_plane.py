"""Production data plane: the host→device staging ring.

BENCH_r05 measured host-to-device at 13.8 MB/s — every batch paid a
BLOCKING `device_put` on the training thread, serialized against the
step it was feeding.  This module is the io tier that removes that
serialization:

* `H2DRing` — a pinned-style, double-buffered **staging ring**: batches
  are assembled into REUSABLE preallocated host staging buffers (one
  `np.copyto` per input, which also applies the model's dtype cast — no
  per-batch allocator churn, and on hosts with pinned-memory transfer
  managers the stable buffers are what makes DMA engage), transferred
  to the device by a dedicated ``mx-io-h2d`` thread, and parked in a
  bounded **device-resident prefetch queue** (depth
  ``MXNET_IO_PREFETCH``, floor 2).  Batch k+1 decodes and transfers
  while batch k computes; the consumer never blocks on `device_put` —
  it pops an already-resident device batch.
* `DevicePrefetchIter` — wraps any `DataIter` with the ring.
  `Module.fit` wraps its training iterator automatically
  (``MXNET_IO_RING``, default on) and binds the fused train step's
  placement, so the batches the ring emits are EXACTLY the arrays the
  fused dispatch would have staged — `_stage_inputs` adopts them by
  sharding identity and the step program signature never moves (zero
  steady-state recompiles).  Checkpoint capture/seek, guardian
  quarantine and record-range attribution all delegate to the inner
  iterator, so resume and bad-data bookkeeping are unchanged.
* `DevicePrefetchLoader` — the same ring over a Gluon
  ``DataLoader``-style iterable of ``(data, label)`` pairs
  (`gluon.contrib.estimator.Estimator.fit` wraps with it).
* `auto_shard()` — per-host input sharding: resolves this process's
  ``(part_index, num_parts)`` from the supervisor/dist environment
  (``DMLC_RANK``/``DMLC_NUM_WORKER`` — rewritten by shrink-and-resume,
  so a re-shard lands at the next epoch fence) or the jax multi-process
  runtime.  `ImageRecordIter`/`ImageIter` accept ``num_parts='auto'``
  and re-resolve at every `reset()`.

Telemetry: every transfer runs under an ``io.h2d`` trace span (mxtrace
shows input overlap against ``fit.step``), and the ring registers its
stats under the ``io.*`` dotted namespace in the obs MetricsRegistry —
prefetch depth, occupancy, stalls, bytes, decode-worker queue depth.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

import numpy as _np

from .base import MXNetError
from .analysis import locks as _alocks
from .io import DataBatch, DataIter
from .ndarray.ndarray import NDArray

__all__ = ["H2DRing", "RingPlacement", "DevicePrefetchIter",
           "DevicePrefetchLoader", "auto_shard", "stats"]


def auto_shard(part_index=None, num_parts=None):
    """Resolve this process's input shard as ``(part_index,
    num_parts)``.

    Explicit values win.  Otherwise the dist/supervisor environment
    (``DMLC_RANK``/``DMLC_NUM_WORKER`` — the variables shrink-and-resume
    rewrites when the pod loses a host, so readers that re-resolve at
    reset() re-shard on the epoch fence) is consulted first, then the
    jax multi-process runtime; a single-process run reads (0, 1)."""
    import os
    import sys
    if num_parts not in (None, 0, "auto"):
        return int(part_index or 0), int(num_parts)
    nw = os.environ.get("DMLC_NUM_WORKER")
    if nw and int(nw) > 1:
        return int(os.environ.get("DMLC_RANK", 0)), int(nw)
    if "jax" in sys.modules:
        try:
            import jax
            if jax.process_count() > 1:
                return int(jax.process_index()), int(jax.process_count())
        except Exception:
            pass
    return 0, 1


# ---------------------------------------------------------------------------
# io.* metrics (obs MetricsRegistry)
# ---------------------------------------------------------------------------

_rings = weakref.WeakSet()      # live rings (occupancy/depth at scrape)
_registered = False
# process-lifetime totals: a ring's counts must survive the ring (fit
# wrappers are released when fit returns; the bench io lane reads
# before/after deltas of these)
_TOTALS = {"stalls": 0, "stall_s": 0.0, "batches": 0, "bytes": 0,
           "h2d_s": 0.0, "staging_copies": 0, "zero_copy": 0}
_totals_lock = None


def _totals_guard():
    global _totals_lock
    if _totals_lock is None:
        _totals_lock = _alocks.make_lock("io.totals")
    return _totals_lock


def _totals_add(**kw):
    with _totals_guard():
        for k, v in kw.items():
            _TOTALS[k] += v


def _metrics():
    from .obs import metrics as _m
    return _m


def _register_producer():
    """Register the ``io`` stats producer once (module-level function:
    the registry holds plain callables strongly, and the module never
    dies)."""
    global _registered
    if _registered:
        return
    _registered = True
    try:
        _metrics().register_producer("io", stats)
    except Exception:
        pass


def stats():
    """Io-tier stats (the ``io`` metrics producer): process-lifetime
    totals (stalls, batches, bytes, h2d seconds, staging/zero-copy
    counts — these survive individual rings) plus the LIVE rings'
    count, configured prefetch depth, and current queue occupancy."""
    with _totals_guard():
        out = dict(_TOTALS)
    out.update({"rings": 0, "prefetch_depth": 0, "occupancy": 0})
    for ring in list(_rings):
        s = ring.ring_stats()
        out["rings"] += 1
        out["prefetch_depth"] = max(out["prefetch_depth"], s["depth"])
        out["occupancy"] += s["occupancy"]
    if out["h2d_s"] > 0:
        out["h2d_MBps"] = out["bytes"] / out["h2d_s"] / 1e6
    return out


# ---------------------------------------------------------------------------
# placement: where (and as what dtype) staged batches land
# ---------------------------------------------------------------------------

class RingPlacement:
    """Target of the ring's transfers: a jax sharding (or device) plus
    the per-input target dtypes.

    ``dtypes[i]`` of None keeps input i's dtype (labels — the fused
    step's `_stage_inputs` never casts label inputs, and the ring must
    land bit-identical arrays so the dispatch adopts them without a
    second transfer or a signature change)."""

    def __init__(self, sharding=None, dtypes=None, device=None):
        if sharding is None and device is None:
            from .context import current_context
            device = current_context().jax_device
        self.sharding = sharding if sharding is not None else device
        self.dtypes = list(dtypes) if dtypes is not None else None
        self._is_default = None   # resolved on first put()

    @classmethod
    def for_fused_step(cls, fs):
        """The fused train step's exact staging target: its data
        sharding and, per input, the bound argument's dtype (labels
        uncast) — what `_stage_inputs` would produce, computed once."""
        label_names = set(fs._mod._exec_group.label_names)
        dtypes = []
        for name in fs._input_names:
            if name in label_names:
                dtypes.append(None)
            else:
                dtypes.append(_np.dtype(fs._exec0.arg_dict[name].dtype))
        return cls(sharding=fs._data_sharding, dtypes=dtypes)

    def target_dtype(self, i, arr):
        if self.dtypes is None or i >= len(self.dtypes) or \
                self.dtypes[i] is None:
            return arr.dtype
        return self.dtypes[i]

    def put(self, host_arrays):
        """One batched transfer of every input to the target sharding.

        When the target is the process's plain default device the
        sharding argument is omitted: `device_put` may then ADOPT a
        suitably aligned host buffer zero-copy — the cheapest possible
        h2d, and safe because the ring retires adopted staging buffers
        from reuse (`H2DRing._adopted`)."""
        import jax
        tgt = self.sharding
        if self._is_default is None:
            from jax.sharding import SingleDeviceSharding
            try:
                dev = tgt.device if isinstance(tgt, SingleDeviceSharding) \
                    else tgt if not hasattr(tgt, "device_set") else None
                self._is_default = dev is not None and \
                    dev == jax.local_devices()[0]
            except Exception:
                self._is_default = False
        if self._is_default:
            return jax.device_put(list(host_arrays))
        return jax.device_put(list(host_arrays), tgt)


class _EndOfData:
    """Queue sentinel: the producer exhausted its source (or died with
    `exc`)."""

    __slots__ = ("exc",)

    def __init__(self, exc=None):
        self.exc = exc


class H2DRing:
    """The staging ring itself: reusable host staging slots, one
    transfer path, a bounded device-resident queue.

    The PRODUCER side (`put`) runs on the feeder thread: it assembles
    the batch into the next free staging-slot buffers (dtype cast
    included), issues ONE batched `device_put` to the placement, waits
    for the transfer (in the producer thread — the consumer never
    does), and enqueues the device arrays.  `put` blocks while the
    queue is full: bounded, backpressured — a slow consumer pauses
    decode instead of accumulating batches.  The CONSUMER side (`get`)
    pops a ready device batch; an empty queue is a counted **stall**
    (the pipeline failed to hide the input latency).
    """

    def __init__(self, placement, depth=None, staging=None, name="ring"):
        from . import config as _config
        if depth is None:
            depth = int(_config.get("MXNET_IO_PREFETCH"))
        self.depth = max(2, int(depth))   # device-resident prefetch >= 2
        if staging is None:
            staging = bool(_config.get("MXNET_IO_STAGING"))
        self._staging = staging
        self._placement = placement
        self.name = str(name)
        self._q = collections.deque()
        self._cond = _alocks.make_condition(name="io.ring")
        self._closed = False
        # single-producer serialization + epoch token: put() is
        # designed for one feeder, but a feeder whose join timed out
        # (wedged inner iterator) can wake AFTER a restart — the lock
        # keeps two producers out of the staging slots, and the token
        # (bumped by every reopen) makes the stale thread's put/put_end
        # a rejected no-op instead of a stale batch or premature EOF
        self._put_lock = _alocks.make_lock("io.ring.put")
        self._token = 0
        # double-buffered staging: two rotating buffer SETS — the set
        # filled for batch k+1 is never the one batch k's transfer just
        # drained (the transfer is awaited before enqueue, so two slots
        # are sufficient; the rotation keeps the contract explicit)
        self._slots = [dict(), dict()]
        self._slot_i = 0
        self._adopt_possible = None   # resolved on first transfer
        self._ended = None            # _EndOfData once the source dried
        self._stats = {"stalls": 0, "stall_s": 0.0, "batches": 0,
                       "bytes": 0, "h2d_s": 0.0, "staging_copies": 0,
                       "zero_copy": 0}
        self._stats_lock = _alocks.make_lock("io.ring.stats")
        _rings.add(self)
        _register_producer()

    # -- producer side -------------------------------------------------------
    def _may_adopt(self):
        """Whether this placement's backend can adopt host numpy
        memory zero-copy at all.  Only the CPU backend does (its device
        memory IS host memory); a DMA backend (real TPU/GPU) always
        copies — and there `np.asarray(shard)` would be a full
        device-to-host readback, so the per-buffer adoption check must
        never run.  Unknown platforms are treated as adopting
        (correctness over recycling: their buffers just never reuse)."""
        if self._adopt_possible is None:
            try:
                import jax
                tgt = self._placement.sharding
                devs = list(getattr(tgt, "device_set", None) or ())
                if not devs:
                    devs = [tgt if hasattr(tgt, "platform")
                            else getattr(tgt, "_device", None) or
                            jax.local_devices()[0]]
                self._adopt_possible = all(
                    getattr(d, "platform", "cpu") == "cpu" for d in devs)
            except Exception:
                self._adopt_possible = True
        return self._adopt_possible

    @staticmethod
    def _adopted(dev, buf):
        """True when the transfer ADOPTED `buf`'s memory zero-copy
        instead of copying it (the CPU backend does this for suitably
        aligned arrays, per shard).  An adopted buffer must never be
        refilled — the device array IS that memory.  Only called when
        `_may_adopt()` (np.asarray is then a zero-copy view, never a
        readback).  When aliasing cannot be disproven the buffer is
        treated as adopted (retired from reuse): correctness over
        recycling."""
        try:
            shards = getattr(dev, "addressable_shards", None) or ()
            views = [s.data for s in shards] or [dev]
            return any(_np.shares_memory(_np.asarray(v), buf)
                       for v in views)
        except Exception:
            return True

    def _assemble(self, arrays):
        """Host staging: copy (+cast) each input into this slot set's
        reusable buffer.  A changed shape/dtype (epoch-tail batch)
        reallocates that one buffer; a buffer the backend adopted
        zero-copy was retired by the previous transfer and is
        reallocated here too — on such backends the 'copyto + adopt'
        pair IS the whole h2d path (no second copy ever happens), while
        copying backends (a real TPU's DMA) keep recycling the same
        staging memory, pinned-style."""
        slot = self._slots[self._slot_i]
        self._slot_i = (self._slot_i + 1) % len(self._slots)
        staged = []
        copies = 0
        for j, a in enumerate(arrays):
            a = _np.asarray(a)
            tgt = _np.dtype(self._placement.target_dtype(j, a))
            if not self._staging:
                staged.append(a.astype(tgt) if a.dtype != tgt else a)
                continue
            buf = slot.get(j)
            if buf is None or buf.shape != a.shape or buf.dtype != tgt:
                buf = slot[j] = _np.empty(a.shape, tgt)
            _np.copyto(buf, a, casting="unsafe")
            copies += 1
            staged.append(buf)
        return staged, copies, slot

    def put(self, arrays, meta=None, token=None):
        """Stage + transfer one batch (producer thread).  Blocks while
        the queue is full (backpressure).  Returns False when the ring
        was closed under the wait — or when `token` no longer matches
        the ring's epoch (a stale feeder surviving a restart)."""
        import jax
        from .obs import trace as _trace
        with self._cond:
            self._cond.wait_for(
                lambda: self._closed or token not in (None, self._token)
                or len(self._q) < self.depth)
            if self._closed or token not in (None, self._token):
                return False
        with self._put_lock:
            t0 = time.perf_counter()
            staged, copies, slot = self._assemble(arrays)
            nbytes = sum(int(a.nbytes) for a in staged)
            with _trace.span("io.h2d", cat="io", ring=self.name,
                             bytes=nbytes):
                devs = self._placement.put(staged)
                # the wait lives HERE, on the io thread: the staging
                # slot is free for reuse the moment this returns, and
                # the consumer pops fully-resident arrays
                jax.block_until_ready(devs)
            if self._staging and self._may_adopt():
                # retire any buffer the backend adopted zero-copy: it
                # now BELONGS to the emitted device array and refilling
                # it would silently corrupt an in-flight batch
                for j, (d, b) in enumerate(zip(devs, staged)):
                    if slot.get(j) is b and self._adopted(d, b):
                        del slot[j]
                        with self._stats_lock:
                            self._stats["zero_copy"] += 1
                        _totals_add(zero_copy=1)
            dt = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["batches"] += 1
            self._stats["bytes"] += nbytes
            self._stats["h2d_s"] += dt
            self._stats["staging_copies"] += copies
        _totals_add(batches=1, bytes=nbytes, h2d_s=dt,
                    staging_copies=copies)
        m = _metrics()
        m.counter("io.h2d.batches").inc()
        m.counter("io.h2d.bytes").inc(nbytes)
        with self._cond:
            if self._closed or token not in (None, self._token):
                return False
            self._q.append((devs, meta))
            m.gauge("io.ring.occupancy").set(len(self._q))
            self._cond.notify_all()
        return True

    def put_end(self, exc=None, token=None):
        """Mark the source exhausted (or broken): `get` drains the queue
        then surfaces the end/exception.  A stale feeder's token is
        rejected (its EOF must not truncate the restarted epoch)."""
        with self._cond:
            if token not in (None, self._token):
                return
            self._q.append(_EndOfData(exc))
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------
    def get(self):
        """Pop the oldest ready device batch as ``(device_arrays,
        meta)``; raises StopIteration at end of data — and KEEPS
        raising it on further calls (a drained ring must behave like an
        exhausted iterator, not hang waiting for a feeder that already
        exited).  An empty queue counts (and times) a stall."""
        t0 = None
        with self._cond:
            if not self._q and self._ended is not None:
                if self._ended.exc is not None:
                    raise self._ended.exc
                raise StopIteration
            if not self._q:
                t0 = time.perf_counter()
            self._cond.wait_for(lambda: self._q or self._closed)
            if not self._q and self._closed:
                raise StopIteration
            item = self._q.popleft()
            if isinstance(item, _EndOfData):
                self._ended = item
            _metrics().gauge("io.ring.occupancy").set(len(self._q))
            self._cond.notify_all()
        if t0 is not None and not isinstance(item, _EndOfData):
            # waiting for the end-of-epoch sentinel is not a pipeline
            # stall — only a wait for a REAL batch failed to overlap
            dt = time.perf_counter() - t0
            with self._stats_lock:
                self._stats["stalls"] += 1
                self._stats["stall_s"] += dt
            _totals_add(stalls=1, stall_s=dt)
            _metrics().counter("io.ring.stalls").inc()
        if isinstance(item, _EndOfData):
            if item.exc is not None:
                raise item.exc
            raise StopIteration
        return item

    def reopen(self):
        """Fresh epoch: clear state and return the new producer token
        (hand it to the feeder; a previous feeder's token is dead)."""
        with self._cond:
            self._closed = False
            self._ended = None
            self._q.clear()
            self._token += 1
            self._cond.notify_all()
            return self._token

    def close(self):
        with self._cond:
            self._closed = True
            self._q.clear()
            self._cond.notify_all()

    def ring_stats(self):
        with self._stats_lock:
            s = dict(self._stats)
        with self._cond:
            s["occupancy"] = sum(1 for it in self._q
                                 if not isinstance(it, _EndOfData))
        s["depth"] = self.depth
        return s


def _resolve_placement(placement):
    """Accept a RingPlacement, a callable producing one (lazy binding —
    the fused step may not exist until `init_optimizer`), or None (the
    current context's device, no cast)."""
    if callable(placement) and not isinstance(placement, RingPlacement):
        placement = placement()
    if placement is None:
        placement = RingPlacement()
    return placement


class DevicePrefetchIter(DataIter):
    """Wrap a `DataIter` with the staging ring: a named ``mx-io-h2d``
    feeder thread pulls batches from the inner iterator, stages them
    through `H2DRing`, and `next()` pops device-resident batches —
    `Module.fit` (and any consumer) never blocks on `device_put`.

    Delegation contract: `seek`/`checkpoint_state`/
    `set_checkpoint_state`/`record_range`/`set_quarantine`/
    `apply_quarantine` all route to the inner iterator (the feeder is
    paused around every such call), so elastic checkpointing, guardian
    quarantine, and shard attribution behave exactly as without the
    ring.  Read-ahead never leaks into checkpoint state: resume
    positioning is `seek(nbatch)`-based and the inner state the
    checkpoint captures is position-independent."""

    def __init__(self, data_iter, placement=None, depth=None,
                 staging=None, name="io"):
        super().__init__(getattr(data_iter, "batch_size", 0))
        self._inner = data_iter
        self._placement_src = placement
        self._ring = None
        self._thread = None
        self._stop = threading.Event()
        self._inner_lock = _alocks.make_lock("io.prefetch.inner")
        self._name = name
        self._started = False
        self._cached = None   # iter_next()'s buffered batch

    # -- delegation ----------------------------------------------------------
    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def record_range(self, nbatch):
        return self._inner.record_range(nbatch)

    def checkpoint_state(self):
        with self._inner_lock:
            return self._inner.checkpoint_state()

    def set_checkpoint_state(self, state, nbatch=0):
        self._pause()
        self._inner.set_checkpoint_state(state, nbatch)
        self._restart()

    def seek(self, nbatch):
        self._pause()
        self._inner.seek(nbatch)
        self._restart()

    def set_quarantine(self, log):
        if hasattr(self._inner, "set_quarantine"):
            self._inner.set_quarantine(log)

    def apply_quarantine(self, entries):
        if hasattr(self._inner, "apply_quarantine"):
            self._pause()
            self._inner.apply_quarantine(entries)
            self._restart()

    # -- the feeder thread ---------------------------------------------------
    def _feed(self, stop, token):
        """One epoch's producer.  EVERY failure — the inner iterator,
        staging, the transfer itself (device OOM) — lands in the ring
        as an end event so the consumer raises instead of waiting
        forever on a dead feeder.  `stop`/`token` are per-start: a
        feeder that outlived a timed-out join (wedged inner iterator)
        holds a dead token and cannot deliver stale batches or a
        premature EOF into the restarted epoch."""
        ring = self._ring
        try:
            while not stop.is_set():
                try:
                    with self._inner_lock:
                        batch = self._inner.next()
                except StopIteration:
                    ring.put_end(token=token)
                    return
                data = list(batch.data) + list(batch.label or [])
                arrays = [v._data if isinstance(v, NDArray) else
                          _np.asarray(v) for v in data]
                meta = (len(batch.data), batch.pad, batch.index,
                        batch.bucket_key)
                if not ring.put(arrays, meta, token=token):
                    return               # closed / restarted under us
        except Exception as e:           # surfaced on the consumer thread
            ring.put_end(e, token=token)

    def _start(self):
        if self._ring is None:
            self._ring = H2DRing(_resolve_placement(self._placement_src),
                                 name=self._name)
            from .obs import metrics as _m
            _m.registry().gauge("io.ring.depth").set(self._ring.depth)
        token = self._ring.reopen()
        self._stop = threading.Event()   # per-start: never shared with a
        self._cached = None              # possibly-wedged old feeder
        self._thread = threading.Thread(
            target=self._feed, args=(self._stop, token), daemon=True,
            name="mx-io-h2d")
        self._thread.start()
        self._started = True

    def _pause(self):
        """Stop the feeder and drop read-ahead (the inner iterator is
        about to be repositioned)."""
        if self._thread is None:
            self._started = False
            return
        self._stop.set()
        self._ring.close()
        from .analysis import tsan as _tsan
        _tsan.join_thread(self._thread, 10, owner=type(self).__name__)
        self._thread = None
        self._started = False

    def _restart(self):
        self._start()

    # -- DataIter surface ----------------------------------------------------
    def reset(self):
        self._pause()
        self._inner.reset()
        self._start()

    def next(self):
        cached = getattr(self, "_cached", None)
        if cached is not None:
            self._cached = None
            return cached
        if not self._started:
            self._start()
        devs, meta = self._ring.get()
        n_data, pad, index, bucket_key = meta
        from .context import current_context
        ctx = current_context()
        nds = [NDArray(d, ctx=ctx) for d in devs]
        return DataBatch(data=nds[:n_data], label=nds[n_data:] or None,
                         pad=pad, index=index, bucket_key=bucket_key,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        """DataIter protocol: buffer the fetched batch so the paired
        `next()` returns it (not the one after)."""
        if getattr(self, "_cached", None) is not None:
            return True
        try:
            self._cached = self.next()
            return True
        except StopIteration:
            return False

    def close(self):
        self._pause()
        if self._ring is not None:
            self._ring.close()
        if hasattr(self._inner, "close"):
            try:
                self._inner.close()
            except Exception:
                pass

    def ring_stats(self):
        return self._ring.ring_stats() if self._ring is not None else {}

    def __del__(self):
        try:
            self._pause()
        except Exception:
            pass


class DevicePrefetchLoader:
    """The staging ring over a Gluon ``DataLoader``-style iterable of
    ``(data, label)`` pairs: iteration yields pairs whose arrays are
    already device-resident (NDArray-wrapped), fed by an ``mx-io-h2d``
    thread with bounded read-ahead.  `gluon.contrib.estimator.
    Estimator.fit` wraps its training loader with this when
    ``MXNET_IO_RING`` is on, so the fused Gluon step's `device_put`
    becomes an adoption of an already-placed buffer."""

    def __init__(self, loader, ctx=None, depth=None, name="io.gluon"):
        self._loader = loader
        self._ctx = ctx
        self._depth = depth
        self._name = name
        self._ring = None
        self._thread = None
        self._stop = threading.Event()

    def __len__(self):
        return len(self._loader)

    def _feed(self, it, stop, token):
        ring = self._ring
        try:
            while not stop.is_set():
                try:
                    pair = next(it)
                except StopIteration:
                    ring.put_end(token=token)
                    return
                arrays = [v._data if isinstance(v, NDArray) else
                          _np.asarray(v) for v in pair]
                if not ring.put(arrays, len(pair), token=token):
                    return
        except Exception as e:           # surfaced on the consumer side
            ring.put_end(e, token=token)

    def _stop_feeder(self):
        if self._thread is None:
            return
        self._stop.set()
        if self._ring is not None:
            self._ring.close()
        from .analysis import tsan as _tsan
        _tsan.join_thread(self._thread, 10, owner=type(self).__name__)
        self._thread = None

    close = _stop_feeder

    def __iter__(self):
        self._stop_feeder()
        if self._ring is None:
            device = self._ctx.jax_device if self._ctx is not None else None
            self._ring = H2DRing(RingPlacement(device=device),
                                 depth=self._depth, name=self._name)
        token = self._ring.reopen()
        self._stop = threading.Event()   # per-start (see DevicePrefetchIter)
        self._thread = threading.Thread(
            target=self._feed, args=(iter(self._loader), self._stop, token),
            daemon=True, name="mx-io-h2d")
        self._thread.start()
        ctx = self._ctx
        if ctx is None:
            from .context import current_context
            ctx = current_context()
        ring = self._ring
        def _gen():
            while True:
                try:
                    devs, _n = ring.get()
                except StopIteration:
                    return
                yield tuple(NDArray(d, ctx=ctx) for d in devs)
        return _gen()
