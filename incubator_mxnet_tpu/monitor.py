"""Per-layer output monitoring (reference `python/mxnet/monitor.py:33`,
backed by `MXExecutorSetMonitorCallback` → our Executor.set_monitor_callback).

Installs on training executors AND on serving executors
(`serving.ServedModel` exposes the same `set_monitor_callback` face): on
the request path the callback fires over the BATCHED outputs of each
executed bucket, and the micro-batcher drives `tic`/`toc_print` around
every batch the way the fit loop does.  Serving executors keep no
persistent per-layer arg arrays, so the arg sweeps degrade gracefully to
whatever the executor exposes, and stat functions may return plain
numbers (a float over a batched output) as well as NDArrays.
"""
from __future__ import annotations

import logging
import re

import numpy as _np

from .ndarray.ndarray import NDArray


class Monitor:
    """Collect per-output statistics every `interval` batches."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.abs().sum() / x.size
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        """Install on anything exposing `set_monitor_callback` — an
        `Executor` or a serving executor (`serving.ServedModel`)."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def _wait_args(self):
        for exe in self.exes:
            for array in getattr(exe, "arg_arrays", ()) or ():
                if array is not None:
                    array.wait_to_read()

    def tic(self):
        if self.step % self.interval == 0:
            self._wait_args()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self._wait_args()
        for exe in self.exes:
            for name, array in (getattr(exe, "arg_dict", None) or {}).items():
                if array is not None and self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                # a stat_func over batched serving outputs may return a
                # plain number / numpy value; render it as-is
                res.append((n, k, str(_np.asarray(v_list)) + "\t"))
                continue
            s = ""
            for v in v_list:
                if not isinstance(v, NDArray):
                    s += str(_np.asarray(v)) + "\t"
                elif v.shape == (1,) or v.shape == ():
                    s += str(v.asnumpy().reshape(-1)[0]) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
