"""One tested O_APPEND JSONL writer for every event sink in the tree.

Three subsystems grew hand-rolled newline-delimited JSON appenders —
the resilience fault log (``MXNET_FAULTS_LOG``), the concurrency
sanitizer dump (``MXNET_TSAN_LOG``), and the training guardian's
quarantine file — each re-implementing the same two invariants:

* **line atomicity** — the file is opened ``O_APPEND`` and each entry
  is ONE ``os.write`` of one ``\\n``-terminated line, so every process
  of a multi-host chaos run can share a single log file without
  interleaving or clobbering each other's events (POSIX makes each
  append atomic);
* **provenance stamping** — every entry names its emitting process
  (pid), its DMLC rank when the launcher set one (read per write — the
  shrink-and-resume path re-ranks a live process mid-run), and its
  thread name, so an artifact line is attributable to the router
  health loop vs a dispatch thread vs a supervisor heartbeat, not just
  to "the process".

This module is that one implementation.  `sink(path)` returns a
process-wide shared `JsonlSink` per path (the fd is opened lazily and
cached); `JsonlSink.write(entry)` stamps and appends, swallowing
``OSError`` — an observability sink must never take the instrumented
code path down.  Stamps use ``setdefault``: an entry that already
carries a field (a pre-stamped event forwarded from another layer)
keeps its own value.

The distributed-tracing span stream (`obs.trace`) writes through this
sink too, which is what makes ``tools/mxtrace.py``'s cross-process
merge trivial: every process of a run appends spans to one shared
file, one line per span.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["JsonlSink", "sink", "stamp", "read_jsonl", "close_all"]

# one shared compact encoder: the span flusher serializes thousands of
# events per flush, and the default encoder's whitespace costs real
# time at that rate
_dumps = json.JSONEncoder(separators=(",", ":"), default=str).encode

_sinks = {}
_sinks_lock = threading.Lock()   # plain: this module must stay import-light

# getpid is a real syscall on hardened containers (measured ~8us under
# seccomp) and stamping is per event: cache it, refreshed after fork
_PID = [os.getpid()]
if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _PID.__setitem__(0, os.getpid()))


def stamp(entry):
    """Add pid / rank / thread / time provenance to `entry` in place
    (pre-stamped fields win — producers that capture their emitting
    thread before handing records to a background writer keep it) and
    return it.  Field work is lazy: this runs once per event."""
    if "pid" not in entry:
        entry["pid"] = _PID[0]
    if "thread" not in entry:
        entry["thread"] = threading.current_thread().name
    if "rank" not in entry:
        rank = os.environ.get("DMLC_RANK")
        entry["rank"] = int(rank) if rank is not None \
            and rank.isdigit() else None
    if "time" not in entry:
        entry["time"] = round(time.time(), 3)
    return entry


class JsonlSink:
    """Append-only JSONL file: one stamped, line-atomic write per entry."""

    def __init__(self, path):
        self.path = str(path)
        self._fd = None
        self._open_lock = threading.Lock()
        self.written = 0
        self.errors = 0

    def _ensure_fd(self):
        """The one fd per sink, opened exactly once (two threads of a
        shared process-wide sink racing the lazy open must not leak a
        second fd).  O_APPEND: every write() lands atomically, so all
        processes/threads of a chaos run share one file without
        interleaving mid-line."""
        if self._fd is None:
            with self._open_lock:
                if self._fd is None:
                    self._fd = os.open(
                        self.path,
                        os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        return self._fd

    def write(self, entry):
        """Stamp and append one entry as a single line.  Returns the
        stamped entry (callers that also keep an in-memory trace reuse
        it).  IO errors are counted, never raised."""
        stamp(entry)
        try:
            os.write(self._ensure_fd(), (_dumps(entry) + "\n").encode())
            self.written += 1
        except OSError:
            self.errors += 1
        return entry

    def write_many(self, entries):
        """Append a batch of stamped entries with ONE write: each line
        is still intact (the single append lands atomically), and the
        per-entry syscall cost amortizes — this is the span flusher's
        path, where a write per span would tax the traced hot path."""
        # batch-level stamping: the rank env read and the wall-clock
        # round cost microseconds EACH at per-entry rate; one value per
        # batch is exact for rank and coarse-but-unused for time on
        # span records (they carry their own ts)
        rank = os.environ.get("DMLC_RANK")
        rank = int(rank) if rank is not None and rank.isdigit() else None
        now = round(time.time(), 3)
        pid = _PID[0]
        thread = threading.current_thread().name
        blob = bytearray()
        n = 0
        for e in entries:
            if "pid" not in e:
                e["pid"] = pid
            if "thread" not in e:
                e["thread"] = thread
            if "rank" not in e:
                e["rank"] = rank
            if "time" not in e:
                e["time"] = now
            try:
                blob += (_dumps(e) + "\n").encode()
                n += 1
            except (TypeError, ValueError):
                self.errors += 1
        if not n:
            return
        try:
            os.write(self._ensure_fd(), bytes(blob))
            self.written += n
        except OSError:
            self.errors += 1

    def write_rendered(self, lines):
        """Append pre-rendered JSON lines (no trailing newline) with
        ONE write — the span flusher's fast path: its records have a
        fixed schema it renders itself (`obs.trace._render`), skipping
        the generic encoder."""
        if not lines:
            return
        try:
            os.write(self._ensure_fd(),
                     ("\n".join(lines) + "\n").encode())
            self.written += len(lines)
        except OSError:
            self.errors += 1

    def close(self):
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


def sink(path):
    """The process-wide shared sink for `path` (one fd per file, every
    subsystem appending to the same log shares it)."""
    path = str(path)
    with _sinks_lock:
        s = _sinks.get(path)
        if s is None:
            s = _sinks[path] = JsonlSink(path)
        return s


def read_jsonl(path):
    """Every parseable entry in a JSONL file, oldest first (damaged
    lines — a process killed mid-append on a non-POSIX fs — are
    skipped, not fatal)."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def close_all():
    """Close every cached fd (tests that rotate tmp dirs)."""
    with _sinks_lock:
        sinks = list(_sinks.values())
        _sinks.clear()
    for s in sinks:
        s.close()
