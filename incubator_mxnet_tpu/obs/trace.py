"""Cross-process distributed tracing: trace-id/span-id context over
the transport frames, spans as shared-file JSONL.

The profiler's chrome trace answers "what did THIS process spend time
on"; it cannot answer "where did this request/step spend its time
ACROSS processes" — a routed request crosses router -> transport ->
subprocess worker -> batcher -> fused execute, and a training step
crosses fit -> kvstore push -> parameter server.  This module adds the
missing correlation:

* a **span** is one timed operation with a ``trace`` id (the whole
  request/step), its own ``span`` id, and a ``parent`` span id — ids
  are ``pid``-prefixed counters, unique across every process of a run
  with zero coordination;
* the current span rides a ``contextvars`` context; `span()` opens a
  child of whatever is current (or a new root);
* **propagation**: the dist transport injects the current span as a
  ``tr`` frame field on every request (`rpc_span`), and every server
  handler (replica worker, host daemon, parameter server) adopts it
  (`server_span`) — so the worker-side execute span is a CHILD of the
  router-side dispatch span, in another process;
* finished spans append to a **shared JSONL file** (`obs.jsonl_sink`
  — O_APPEND line-atomic, pid/thread-stamped), one line per span, so
  every process of a run writes the same file and
  ``tools/mxtrace.py`` merges them into one Perfetto-loadable chrome
  trace where a single request reads as one connected tree with flow
  arrows across process lanes.

Enabled by pointing ``MXNET_OBS_TRACE`` at the shared span file (the
env propagates to spawned workers/daemons) or `enable(path)`.  Off,
every hook is a single global read returning a shared no-op span.  The
in-memory buffer is bounded (``MXNET_OBS_TRACE_BUFFER``, drop-oldest
with a ``dropped`` counter surfaced as a metric); it auto-flushes
every ``_FLUSH_EVERY`` spans and at exit, and explicitly via
`flush()`.
"""
from __future__ import annotations

import atexit
import contextlib
import contextvars
import itertools
import os
import threading
import time

from . import jsonl_sink as _jsonl

__all__ = ["enabled", "enable", "disable", "flush", "stats",
           "span", "start_span", "record_span", "current_frame",
           "activate", "rpc_span", "server_span", "NULL_SPAN"]

_ctx = contextvars.ContextVar("mx_obs_trace", default=None)

_FLUSH_EVERY = 512

_lock = threading.Lock()
_enabled = None            # tri-state: None = read MXNET_OBS_TRACE lazily
_path = None
_buffer = []
_cap = None
_dropped = 0
_flushed = 0
_ended = 0
_atexit_armed = False
_flush_event = threading.Event()
_flusher = [None]
# observability of the observability: nanoseconds the background
# flusher spent serializing + writing spans (the increment races are
# benign — it is a counter).  Exposed as 'trace.self_time_ms' in the
# metrics scrape; the obs CI gate pairs it with a single-threaded
# calibration of the per-span hook cost (`calibrate_span_cost`) —
# in-hook wall timing under thread contention would count GIL waits
# as telemetry cost.
_self_ns = [0]
# pid-prefixed ids: unique across processes with zero coordination (the
# pid is cached — a syscall per span id would tax the hot path — and
# refreshed after fork so a forked child's ids diverge)
_ids = itertools.count(1)
_PID = [os.getpid()]
_id_prefix = ["%x-" % _PID[0]]


def _refresh_pid():
    _PID[0] = os.getpid()
    _id_prefix[0] = "%x-" % _PID[0]


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_refresh_pid)


def _id(kind):
    return kind + _id_prefix[0] + str(next(_ids))


# span timestamps are wall-clock us (time.time_ns() // 1000), not
# perf_counter: spans from DIFFERENT processes must land on one
# comparable timeline in the merged trace


def enabled():
    global _enabled, _path, _cap
    if _enabled is None:
        with _lock:
            if _enabled is None:
                from .. import config as _config
                path = str(_config.get("MXNET_OBS_TRACE") or "")
                _path = path or None
                _cap = max(int(_config.get("MXNET_OBS_TRACE_BUFFER")), 16)
                _enabled = bool(path)
        if _enabled:
            _arm_atexit()
            _ensure_flusher()
    return _enabled


def enable(path=None):
    """Turn tracing on programmatically; `path` (optional) is the
    shared span JSONL file — without one, spans stay in the bounded
    in-memory buffer (tests read them via `buffered()`)."""
    global _enabled, _path, _cap
    enabled()   # resolve knobs first so this override wins
    with _lock:
        _enabled = True
        if path is not None:
            _path = str(path)
        has_path = _path is not None
    _arm_atexit()
    if has_path:
        _ensure_flusher()


def disable():
    global _enabled
    enabled()
    with _lock:
        _enabled = False


def _arm_atexit():
    global _atexit_armed
    if _atexit_armed:
        return
    _atexit_armed = True
    atexit.register(flush)
    # the span plane's own counters join the scrape ('trace.dropped'
    # is how silent span loss becomes visible)
    from . import metrics as _metrics
    _metrics.register_producer("trace", stats)


def stats():
    """Span-plane counters (registered as the ``trace`` metrics
    namespace when tracing is enabled)."""
    with _lock:
        return {"buffered": len(_buffer), "dropped": _dropped,
                "flushed": _flushed, "ended": _ended,
                "self_time_ms": _self_ns[0] / 1e6,
                "enabled": bool(_enabled)}


def self_time_ns():
    """Nanoseconds the flusher spent serializing + writing spans."""
    return _self_ns[0]


def calibrate_span_cost(n=8192, scratch=None):
    """Measured ALL-IN cost of one span in seconds — open + close +
    buffering + its share of serialization and write IO — from a
    single-threaded loop in this process (no thread preemption to
    inflate the numbers).  The obs CI gate multiplies this by the
    spans-per-request observed in the traced run to compute the
    hot-path overhead ratio deterministically; requires tracing to be
    enabled with a file.

    The synthetic spans land in a SCRATCH file (a throwaway temp file
    unless `scratch` names one), never the run's shared span file —
    merged traces and their orphan/span-count gates must see only real
    workload spans."""
    global _path
    if not enabled() or _path is None:
        return None
    flush()
    if scratch is None:
        import tempfile
        fd, scratch = tempfile.mkstemp(prefix="mxobs_cal_",
                                       suffix=".jsonl")
        os.close(fd)
    saved, _path = _path, str(scratch)
    try:
        t0 = time.perf_counter_ns()
        done = 0
        while done < n:
            # emit in sub-threshold batches then flush synchronously,
            # so the background flusher never interleaves the timing
            for i in range(256):
                sp = start_span("calibrate.span", cat="calibrate",
                                rid=f"c-{done + i}",
                                priority="interactive")
                sp.end(outcome="ok")
            flush()
            done += 256
        return (time.perf_counter_ns() - t0) / done / 1e9
    finally:
        flush()
        _path = saved


def _as_dict(rec):
    tr, sp, pa, name, cat, ts, dur, args, thread = rec
    return {"k": "span", "tr": tr, "sp": sp, "pa": pa, "name": name,
            "cat": cat, "ts": ts, "dur": dur, "args": args,
            "thread": thread, "pid": _PID[0]}


def buffered():
    """Unflushed span records as dicts (tests; file-less mode)."""
    with _lock:
        return [_as_dict(r) for r in _buffer[:len(_buffer)]]


_SAFE_DUMPS = _jsonl._dumps


def _render(rec):
    """One span tuple -> its JSONL line.  Hand-rendered: the generic
    json encoder costs ~4us per span dict at flush rate, which the
    calibrated overhead gate charges straight to the hot path.  Ids,
    cats, and our span names are controlled identifiers (no escaping);
    anything potentially carrying quotes (args values, thread names,
    caller-supplied names) goes through the real encoder."""
    tr, sp, pa, name, cat, ts, dur, args, thread = rec
    return (
        '{"k":"span","tr":"%s","sp":"%s","pa":%s,"name":%s,"cat":"%s",'
        '"ts":%d,"dur":%d,"pid":%d,"thread":%s,"args":%s}'
        % (tr, sp,
           '"%s"' % pa if pa else "null",
           '"%s"' % name if '"' not in name and "\\" not in name
           else _SAFE_DUMPS(name),
           cat, ts, dur, _PID[0],
           '"%s"' % thread if '"' not in thread and "\\" not in thread
           else _SAFE_DUMPS(thread),
           _SAFE_DUMPS(args) if args else "{}"))


def reset():
    """Drop buffered spans and counters; keep enablement (tests)."""
    global _dropped, _flushed, _ended
    with _lock:
        _buffer.clear()
        _dropped = _flushed = _ended = 0
        _self_ns[0] = 0


def flush():
    """Write every buffered span to the shared file, one line each.
    The lock serializes FLUSHERS only — recorders append lock-free
    (GIL-atomic), and taking the first n elements then deleting them
    cannot race appends, which only ever extend the tail."""
    global _flushed
    t0 = time.perf_counter_ns()
    with _lock:
        n = len(_buffer)
        path = _path
        if not n or path is None:
            return 0
        batch = _buffer[:n]
        del _buffer[:n]
    lines = []
    for rec in batch:
        try:
            lines.append(_render(rec))
        except (TypeError, ValueError):
            continue   # unserializable args: drop the span, not the run
    _jsonl.sink(path).write_rendered(lines)
    _flushed += n
    _self_ns[0] += time.perf_counter_ns() - t0
    return n


def _flush_loop():
    """The background flusher: serialization + the write syscall are
    paid HERE, never on the traced hot path (`_record` only appends to
    the in-memory buffer).  Wakes on the threshold signal or every
    0.5s, whichever first; the atexit flush drains the tail."""
    while True:
        _flush_event.wait(timeout=0.5)
        _flush_event.clear()
        try:
            flush()
        except Exception:
            pass    # the flusher must never die mid-run


def _ensure_flusher():
    t = _flusher[0]
    if t is not None and t.is_alive():
        return
    t = threading.Thread(target=_flush_loop, daemon=True,
                         name="mx-obs-trace-flush")
    _flusher[0] = t
    t.start()


def _record(tr, sp, pa, name, cat, ts, dur, args):
    """Buffer one finished span as a TUPLE (rendered to JSON by the
    flusher).  LOCK-FREE on the hot path: a list append is atomic
    under the GIL, and a contended lock here costs a futex syscall per
    span across every serving/dispatch thread (measured ~3x the span's
    own cost).  The cap trim takes the lock only when actually over
    cap (file-less buffering — the flusher normally drains long
    before).  The emitting thread is captured HERE: stamping at flush
    time would attribute every span to the flusher thread."""
    global _dropped, _ended
    _buffer.append((tr, sp, pa, name, cat, ts, dur, args,
                    threading.current_thread().name))
    _ended += 1                      # benign race: it is a counter
    n = len(_buffer)
    cap = _cap or 65536
    if n > cap:
        with _lock:
            while len(_buffer) > cap:
                _buffer.pop(0)
                _dropped += 1
    elif n >= _FLUSH_EVERY and _path is not None \
            and not _flush_event.is_set():
        _flush_event.set()


class SpanHandle:
    """One live span; `end()` exactly once buffers the record."""

    __slots__ = ("trace", "span", "parent", "name", "cat", "t0", "args",
                 "_done")

    def __init__(self, name, trace, parent, cat, args):
        self.name = name
        self.trace = trace
        self.span = _id("s")
        self.parent = parent
        self.cat = cat
        self.t0 = time.time_ns() // 1000
        self.args = args
        self._done = False

    def frame(self):
        """The wire form carried in a transport frame's ``tr`` field."""
        return {"t": self.trace, "s": self.span}

    def note(self, **args):
        self.args.update(args)
        return self

    def end(self, **args):
        if self._done:
            return
        self._done = True
        if args:
            self.args.update(args)
        _record(self.trace, self.span, self.parent, self.name, self.cat,
                self.t0, time.time_ns() // 1000 - self.t0, self.args)


class _NullSpan:
    """The shared off-switch: every hook returns this when tracing is
    disabled — no allocation, no time reads."""

    __slots__ = ()
    trace = span = parent = None

    def frame(self):
        return None

    def note(self, **args):
        return self

    def end(self, **args):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NULL_SPAN = _NullSpan()


def current_frame():
    """The current span's wire form ({"t","s"}) or None."""
    return _ctx.get()


def start_span(name, parent=None, cat="span", **args):
    """Open a span (manual end).  ``parent`` is a wire frame
    ({"t","s"}) — defaults to the current context; None there starts a
    new trace.  Does NOT touch the context (async owners like the
    router hold the handle and `activate()` it where child work
    happens)."""
    if not enabled():
        return NULL_SPAN
    if parent is None:
        parent = _ctx.get()
    if parent:
        return SpanHandle(name, parent["t"], parent["s"], cat, args)
    return SpanHandle(name, _id("t"), None, cat, args)


def record_span(name, ts_us, dur_us, parent=None, cat="span", **args):
    """Buffer an already-timed span (post-hoc instrumentation sites)."""
    if not enabled():
        return
    if parent is None:
        parent = _ctx.get()
    trace = parent["t"] if parent else _id("t")
    _record(trace, _id("s"), parent["s"] if parent else None, str(name),
            cat, int(ts_us), int(dur_us), args)


class _Activation:
    """Tiny context manager making a frame current (class-based: this
    sits on the router dispatch hot path, where a contextlib generator
    costs real microseconds under the GIL)."""

    __slots__ = ("_frame", "_token")

    def __init__(self, frame):
        self._frame = frame
        self._token = None

    def __enter__(self):
        if self._frame is not None:
            self._token = _ctx.set(self._frame)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _ctx.reset(self._token)


def activate(handle_or_frame):
    """Make a span (or wire frame) the current context for the body —
    children opened inside parent to it, transport requests inject it."""
    frame = handle_or_frame.frame() \
        if isinstance(handle_or_frame, (SpanHandle, _NullSpan)) \
        else handle_or_frame
    return _Activation(frame)


@contextlib.contextmanager
def span(name, cat="span", parent=None, **args):
    """Timed child span of the current context, active for the body."""
    if not enabled():
        yield NULL_SPAN
        return
    sp = start_span(name, parent=parent, cat=cat, **args)
    token = _ctx.set(sp.frame())
    try:
        yield sp
    finally:
        _ctx.reset(token)
        sp.end()


def rpc_span(msg, peer):
    """Transport-client hook (`dist.transport.Channel`): open a span
    for this request and inject its context as the frame's ``tr``
    field.  An explicit ``tr`` already on the message (a submit-time
    capture from another thread, e.g. `RemoteReplica`) becomes the
    PARENT — the rpc span slots under the request that queued it."""
    if not enabled():
        return NULL_SPAN
    parent = msg.get("tr") or _ctx.get()
    sp = start_span(f"rpc.{msg.get('cmd')}", parent=parent, cat="rpc",
                    peer=str(peer))
    msg["tr"] = sp.frame()
    return sp


@contextlib.contextmanager
def server_span(msg, name, cat="server", **args):
    """Server-handler hook: adopt the frame's ``tr`` as parent, open
    the handling span, and keep it current for the body — the
    cross-process edge of the span tree."""
    if not enabled():
        yield NULL_SPAN
        return
    parent = msg.get("tr") if isinstance(msg, dict) else None
    sp = start_span(name, parent=parent, cat=cat, **args)
    token = _ctx.set(sp.frame())
    try:
        yield sp
    finally:
        _ctx.reset(token)
        sp.end()
