"""obs — the unified telemetry plane (metrics, tracing, event sinks).

One package, three products, each replacing a grown-per-subsystem
answer with a shared one:

* **metrics.py** — `MetricsRegistry`: counters/gauges/fixed-bucket
  histograms with a lock-cheap hot path; every subsystem's ``stats()``
  registers as a producer under a stable dotted namespace; exported in
  Prometheus text format through a ``metrics`` frame on the dist
  transport (workers, host daemons, and the parameter server answer
  scrapes; `FleetManager.scrape()` aggregates fleet-wide;
  ``tools/mxtop.py`` renders it live).
* **trace.py** — distributed tracing: trace/span ids propagated
  through transport frames (router dispatch -> worker execute, kvstore
  push/pull, supervisor control), spans appended to one shared JSONL
  file across every process of a run; ``tools/mxtrace.py`` merges them
  (plus the fault/quarantine JSONL sinks) into one Perfetto-loadable
  chrome trace with cross-process flow arrows.
* **jsonl_sink.py** — THE O_APPEND line-atomic JSONL writer with
  pid/rank/thread stamping, shared by the fault log, the sanitizer
  dump, the guardian quarantine, and the span stream.

Knobs: ``MXNET_OBS_TRACE`` (span file; enables tracing),
``MXNET_OBS_TRACE_BUFFER`` (span buffer cap), ``MXNET_OBS_METRICS``
(producer collection master switch).  See the README's
"Observability" section for the namespace table and tooling.
"""
from __future__ import annotations

from . import jsonl_sink  # noqa: F401
from . import metrics  # noqa: F401
from . import trace  # noqa: F401
from .metrics import (registry, counter, gauge, histogram,  # noqa: F401
                      register_producer, unregister_producer,
                      render_prometheus, parse_prometheus)

__all__ = ["jsonl_sink", "metrics", "trace", "scrape", "registry",
           "counter", "gauge", "histogram", "register_producer",
           "unregister_producer", "render_prometheus",
           "parse_prometheus"]


def __getattr__(name):
    # scrape imports the transport lazily; keep `import obs` light
    if name == "scrape":
        from . import scrape as _scrape
        return _scrape
    raise AttributeError(name)
