"""The scrape plane: ``metrics`` frames over the dist transport.

Every long-lived process in the system already speaks the
length-prefixed transport frames (replica workers, host daemons, the
parameter server); each of their handlers answers a ``{"cmd":
"metrics"}`` frame with this process's registry snapshot:

    {"ok": True, "values": {dotted.name: number, ...},
     "prom": "<Prometheus text exposition>"}

This module is the shared implementation: `metrics_reply()` builds
that reply (the handlers call it), `scrape(endpoint)` fetches one
process's snapshot over a short-lived channel, and `MetricsEndpoint`
is a standalone server for processes that have no other listener (a
training job under a supervisor, a bench harness) — point
``tools/mxtop.py`` at any of them.

`FleetManager.scrape()` composes these into the fleet-wide view: its
own process's registry plus every host daemon's and every remote
replica's.
"""
from __future__ import annotations

import socketserver
import threading

from . import metrics as _metrics

__all__ = ["metrics_reply", "scrape", "MetricsEndpoint"]


def metrics_reply(seq=None):
    """The one ``metrics``-frame reply shape every handler serves —
    ONE producer sweep renders both forms of the same snapshot."""
    reg = _metrics.registry()
    values = reg.collect()
    return {"ok": True, "values": values,
            "prom": reg.render_prometheus(values=values), "seq": seq}


def scrape(endpoint, timeout=5.0):
    """One process's snapshot: ``{"values": ..., "prom": ...}`` from a
    ``host:port`` / ``:port`` / ``port`` endpoint answering the
    transport ``metrics`` frame.  Raises on unreachable/refusing peers
    — the caller (mxtop, the fleet) decides how dead peers render."""
    from ..dist.transport import Channel, parse_endpoint
    host, port = parse_endpoint(endpoint)
    chan = Channel(host, port, timeout=timeout, connect_wait=timeout)
    try:
        reply = chan.request({"cmd": "metrics"})
    finally:
        chan.close()
    if "error" in reply:
        raise RuntimeError(f"scrape {endpoint}: {reply['error']}")
    return {"values": dict(reply.get("values") or {}),
            "prom": reply.get("prom", "")}


class MetricsEndpoint:
    """A standalone transport listener answering ONLY ``metrics`` (and
    ``hb``) frames from this process's registry — observability for
    processes with no other server (trainers, benches, tests)."""

    def __init__(self, host="127.0.0.1", port=0):
        outer_reply = metrics_reply

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from ..dist.transport import recv_msg, send_msg
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (EOFError, ConnectionError, OSError):
                        break
                    cmd = msg.get("cmd")
                    seq = msg.get("seq")
                    if cmd == "metrics":
                        try:
                            reply = outer_reply(seq=seq)
                        except Exception as exc:
                            reply = {"error": f"scrape failed: {exc}",
                                     "seq": seq}
                    elif cmd == "hb":
                        reply = {"ok": True, "seq": seq}
                    else:
                        reply = {"error": f"metrics endpoint: unknown "
                                          f"cmd {cmd!r}", "seq": seq}
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        break

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)
        self.port = self._server.server_address[1]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1}, daemon=True,
            name="mx-obs-metrics-endpoint")
        self._thread.start()
        return self

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start() if self._thread is None else self

    def __exit__(self, *exc):
        self.close()
