"""MetricsRegistry: counters, gauges, histograms, and a scrape plane.

Before this module the framework had seven unrelated ``stats()``
shapes — KVStore, ReplicaRouter, FleetManager, JobSupervisor,
ServingMetrics, the program cache, the guardian — each invented its
own dict and its own reader.  The registry gives them one product:

* **instruments** — `Counter`, `Gauge`, `Histogram` with a lock-cheap
  hot path (one small per-instrument lock; no registry lock is ever
  taken on a record).  Histograms are fixed-bucket (Prometheus
  semantics: cumulative ``le`` buckets + sum + count), so a week of
  observations costs the same memory as a minute.
* **producers** — every existing ``stats()`` dict registers under a
  stable dotted namespace (``kvstore``, ``router``, ``fleet``,
  ``supervisor``, ``guardian``, ``cache``, ``serving.<model>``,
  ``worker``, ``profiler``, ``io`` — the data plane's h2d ring:
  prefetch depth, occupancy, stalls, bytes, decode queue depth...)
  via `register_producer(ns, fn)`.  The
  callable is only invoked at scrape time, so a registered subsystem
  pays NOTHING between scrapes; bound methods are held weakly, so
  registration can never leak a router or a kvstore.
* **export** — `collect()` flattens instruments + producer dicts into
  one ``{dotted.name: number}`` snapshot; `render_prometheus()` emits
  the Prometheus text exposition format (``mx_`` prefix, sanitized
  names, ``# TYPE`` headers); `parse_prometheus()` is the strict
  parser the CI gate validates scrape output with.

The transport scrape frame (``{"cmd": "metrics"}`` answered by the
replica worker, the host daemon, and the parameter server) serves this
registry's snapshot, `FleetManager.scrape()` aggregates it fleet-wide,
and ``tools/mxtop.py`` renders it live.

The ``MXNET_OBS_METRICS`` knob (default on) gates producer invocation:
off, `collect()` returns instruments only — the paranoid-hot-path
escape hatch.
"""
from __future__ import annotations

import bisect
import re
import weakref

from ..analysis import locks as _locks

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "counter", "gauge", "histogram",
           "register_producer", "unregister_producer",
           "render_prometheus", "parse_prometheus", "flatten"]

# default latency-shaped bucket ladder (ms); +Inf is implicit
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0)

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


class Counter:
    """Monotonic counter.  ``inc()`` is one lock + one add."""

    __slots__ = ("name", "_value", "_lock")
    kind = "counter"

    def __init__(self, name):
        self.name = str(name)
        self._value = 0
        self._lock = _locks.make_lock("obs.metrics")

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def sample(self):
        return {self.name: self.value}


class Gauge:
    """Point-in-time value; ``set``/``inc``/``dec``."""

    __slots__ = ("name", "_value", "_lock")
    kind = "gauge"

    def __init__(self, name):
        self.name = str(name)
        self._value = 0.0
        self._lock = _locks.make_lock("obs.metrics")

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def sample(self):
        return {self.name: self.value}


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus ``le`` semantics).

    ``observe()`` is one lock + a bisect + two adds — O(log buckets),
    O(buckets) memory forever.  `quantile(q)` interpolates from the
    bucket counts (coarse by design; the reservoirs in serving.metrics
    stay the precise per-model source)."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_lock")
    kind = "histogram"

    def __init__(self, name, buckets=DEFAULT_BUCKETS):
        self.name = str(name)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError(f"histogram {name!r}: empty bucket ladder")
        self._counts = [0] * (len(self.bounds) + 1)   # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = _locks.make_lock("obs.metrics")

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self):
        """{"buckets": {le: cumulative}, "sum": s, "count": n}."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum, out = 0, {}
        for bound, c in zip(self.bounds, counts):
            cum += c
            out[bound] = cum
        out[float("inf")] = cum + counts[-1]
        return {"buckets": out, "sum": s, "count": n}

    def quantile(self, q):
        """Approximate q-quantile (0..1) from the bucket counts, or
        None before the first observation."""
        snap = self.snapshot()
        n = snap["count"]
        if not n:
            return None
        target = q * n
        prev_bound, prev_cum = 0.0, 0
        for bound, cum in snap["buckets"].items():
            if cum >= target:
                if bound == float("inf"):
                    return prev_bound
                span = cum - prev_cum
                if span <= 0:
                    return bound
                frac = (target - prev_cum) / span
                return prev_bound + (bound - prev_bound) * frac
            prev_bound, prev_cum = bound, cum
        return prev_bound

    def sample(self):
        snap = self.snapshot()
        out = {f"{self.name}.sum": snap["sum"],
               f"{self.name}.count": snap["count"]}
        for bound, cum in snap["buckets"].items():
            le = "+Inf" if bound == float("inf") else f"{bound:g}"
            out[f"{self.name}.bucket.le={le}"] = cum
        return out


def flatten(namespace, obj, out=None):
    """Flatten a stats() dict into dotted numeric leaves: nested dicts
    recurse, bools become 0/1, numbers pass through, everything else
    (strings, lists, None) is dropped — a scrape is numbers."""
    if out is None:
        out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            flatten(f"{namespace}.{k}" if namespace else str(k), v, out)
    elif isinstance(obj, bool):
        out[namespace] = int(obj)
    elif isinstance(obj, (int, float)):
        out[namespace] = obj
    return out


class MetricsRegistry:
    """Instruments + producers under stable dotted names (module doc)."""

    def __init__(self):
        self._lock = _locks.make_lock("obs.metrics.registry")
        self._instruments = {}      # name -> instrument
        self._producers = {}        # namespace -> callable | WeakMethod

    # -- instruments ---------------------------------------------------------
    def _get(self, name, factory, kind):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = factory()
            elif inst.kind != kind:
                raise TypeError(
                    f"metric {name!r} is a {inst.kind}, not a {kind}")
            return inst

    def counter(self, name):
        return self._get(name, lambda: Counter(name), "counter")

    def gauge(self, name):
        return self._get(name, lambda: Gauge(name), "gauge")

    def histogram(self, name, buckets=DEFAULT_BUCKETS):
        return self._get(name, lambda: Histogram(name, buckets),
                         "histogram")

    # -- producers -----------------------------------------------------------
    def register_producer(self, namespace, fn):
        """Register ``fn() -> dict`` under `namespace` (replaces any
        previous producer there — the newest subsystem instance wins).
        Bound methods are held via `weakref.WeakMethod`, so the
        registry never keeps a dead router/kvstore/guardian alive; a
        collected producer silently drops out of scrapes."""
        if hasattr(fn, "__self__"):
            fn = weakref.WeakMethod(fn)
        with self._lock:
            self._producers[str(namespace)] = fn
        return namespace

    def unregister_producer(self, namespace):
        with self._lock:
            return self._producers.pop(str(namespace), None) is not None

    def producers(self):
        with self._lock:
            return sorted(self._producers)

    def _resolve_producers(self):
        with self._lock:
            items = list(self._producers.items())
        out, dead = [], []
        for ns, fn in items:
            call = fn() if isinstance(fn, weakref.WeakMethod) else fn
            if call is None:
                dead.append(ns)
            else:
                out.append((ns, call))
        if dead:
            with self._lock:
                for ns in dead:
                    self._producers.pop(ns, None)
        return out

    # -- export --------------------------------------------------------------
    def collect(self):
        """One flat {dotted.name: number} snapshot: every instrument
        plus every producer's flattened stats dict.  A producer that
        raises is skipped (and its failure counted) — a broken stats()
        must never take the scrape plane down."""
        from .. import config as _config
        out = {}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            out.update(inst.sample())
        if not _config.get("MXNET_OBS_METRICS"):
            return out
        for ns, call in self._resolve_producers():
            try:
                flatten(ns, call(), out)
            except Exception:
                self.counter("obs.producer_errors").inc()
                out[f"obs.producer_errors.{ns}"] = \
                    out.get(f"obs.producer_errors.{ns}", 0) + 1
        return out

    def render_prometheus(self, values=None):
        """The Prometheus text exposition format over `collect()` plus
        native histogram series for registered Histogram instruments.
        Pass an already-collected ``values`` dict to avoid invoking
        every producer a second time (the scrape reply carries both
        forms of one snapshot)."""
        with self._lock:
            instruments = dict(self._instruments)
        if values is None:
            values = self.collect()
        lines = []
        emitted_hist = set()
        for name, inst in sorted(instruments.items()):
            if inst.kind != "histogram":
                continue
            emitted_hist.add(name)
            prom = _prom_name(name)
            lines.append(f"# TYPE {prom} histogram")
            snap = inst.snapshot()
            for bound, cum in snap["buckets"].items():
                le = "+Inf" if bound == float("inf") else f"{bound:g}"
                lines.append(f'{prom}_bucket{{le="{le}"}} {cum}')
            lines.append(f"{prom}_sum {_prom_value(snap['sum'])}")
            lines.append(f"{prom}_count {snap['count']}")
        for name in sorted(values):
            if any(name == h or name.startswith(h + ".")
                   for h in emitted_hist):
                continue   # rendered as a native histogram series above
            inst = instruments.get(name)
            kind = inst.kind if inst is not None else "gauge"
            prom = _prom_name(name)
            lines.append(f"# TYPE {prom} {kind}")
            lines.append(f"{prom} {_prom_value(values[name])}")
        return "\n".join(lines) + "\n"


def _prom_name(name):
    sanitized = _NAME_SANITIZE.sub("_", str(name))
    return "mx_" + sanitized


def _prom_value(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


_METRIC_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)(?:\s+\d+)?$")
_LABEL = re.compile(r'^\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*$')


def parse_prometheus(text):
    """Strict parser for the text exposition format: returns
    ``{(name, ((label, value), ...)): float}``.  Raises ``ValueError``
    on any malformed line — this is the validity gate the obs CI stage
    runs over scrape output, so it must reject, not guess."""
    out = {}
    for lineno, raw in enumerate(str(text).splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] not in ("HELP", "TYPE"):
                raise ValueError(
                    f"line {lineno}: unknown comment form {line!r}")
            if len(parts) >= 2 and parts[1] == "TYPE" and (
                    len(parts) < 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped")):
                raise ValueError(f"line {lineno}: bad TYPE line {line!r}")
            continue
        m = _METRIC_LINE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: not a metric line {line!r}")
        labels = ()
        if m.group("labels"):
            pairs = []
            for part in m.group("labels").split(","):
                lm = _LABEL.match(part)
                if lm is None:
                    raise ValueError(
                        f"line {lineno}: bad label {part!r}")
                pairs.append((lm.group(1), lm.group(2)))
            labels = tuple(pairs)
        val = m.group("value")
        if val in ("+Inf", "-Inf", "NaN"):
            num = float(val.replace("Inf", "inf").replace("NaN", "nan"))
        else:
            try:
                num = float(val)
            except ValueError:
                raise ValueError(
                    f"line {lineno}: non-numeric value {val!r}") from None
        out[(m.group("name"), labels)] = num
    return out


# -- the process-wide default registry ----------------------------------------
_default = MetricsRegistry()


def registry():
    """The process-wide registry every subsystem registers into and
    every scrape frame serves."""
    return _default


def counter(name):
    return _default.counter(name)


def gauge(name):
    return _default.gauge(name)


def histogram(name, buckets=DEFAULT_BUCKETS):
    return _default.histogram(name, buckets)


def register_producer(namespace, fn):
    return _default.register_producer(namespace, fn)


def unregister_producer(namespace):
    return _default.unregister_producer(namespace)


def render_prometheus():
    return _default.render_prometheus()
