"""Fused public training paths — the whole train step as ONE donated XLA
program.

The reference keeps per-step dispatch cheap with bulk-exec segments
(`src/executor/graph_executor.cc:1194-1316`) and fused optimizer kernels
(`src/operator/optimizer_op.cc`), so `Module.fit`'s forward → backward →
kvstore push/pull → per-parameter-update loop costs little on GPU.  On TPU
every dispatch is a host→device round trip; the TPU-native answer is to
compile the ENTIRE train step — forward, backward, gradient reduction
(data parallel), optimizer for all parameters, BatchNorm aux updates,
metric accumulation, RNG key advance — into one donated XLA program per
input signature, reachable from the public `Module.fit` /
`gluon.Trainer.step` APIs.

Two layers:

* `FusedOptimizer` — applies `Optimizer.update_multi_precision` for every
  parameter in one jitted donated program.  The *public* optimizer objects
  are traced directly (their nd-op math is jax underneath), so every
  registered optimizer keeps its exact semantics — including lr/wd
  multipliers, schedulers, and multi-precision fp32 master weights.
  Hyperparameters that change per step (lr, wd, update count t,
  rescale_grad) are injected as traced scalars so schedules never
  retrigger compilation.  Optimizers whose update cannot trace (e.g. ones
  drawing host RNG) fall back to the per-parameter eager path
  automatically.

* `FusedTrainStep` — used by `Module` (`module/module.py`): whole-graph
  forward+vjp (the Symbol is already one XLA computation) composed with
  the `FusedOptimizer` trace plus aux/metric/key carries.  For multiple
  devices the inputs are sharded over a 1-D `jax.sharding.Mesh` data axis
  with parameters replicated: XLA inserts the gradient all-reduce (the
  `kvstore='device'/'tpu'` reduce becomes a collective inside the
  program) and BatchNorm statistics become global-batch statistics
  (sync-BN semantics, the stronger form of the reference's per-device
  stats).
"""
from __future__ import annotations

import contextlib
import logging

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["FusedOptimizer", "FusedTrainStep", "FusedInference"]

_log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# pytree helpers over optimizer states (None | NDArray | nested tuples)
# ---------------------------------------------------------------------------

def _state_data(s):
    """NDArray-state pytree -> raw jax-array pytree."""
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s._data
    if isinstance(s, (tuple, list)):
        return tuple(_state_data(x) for x in s)
    return s


def _state_wrap(values, ctx):
    """Raw-array pytree -> fresh NDArray shells (used inside the trace so
    the public optimizer's in-place writes land on throwaway wrappers)."""
    import jax
    if values is None:
        return None
    if isinstance(values, (tuple, list)):
        return tuple(_state_wrap(v, ctx) for v in values)
    if isinstance(values, jax.Array) or hasattr(values, "dtype"):
        return NDArray(values, ctx=ctx)
    return values


def _state_write_back(dst, new_values):
    """Write updated raw arrays into the persistent NDArray state pytree."""
    if dst is None:
        return
    if isinstance(dst, NDArray):
        dst._set_data(new_values)
        return
    if isinstance(dst, (tuple, list)):
        for d, v in zip(dst, new_values):
            _state_write_back(d, v)


class _TMap(dict):
    """Stand-in for `Optimizer._index_update_count` during tracing: returns
    the traced per-parameter step count (as an NDArray scalar so optimizer
    float math like ``beta ** t`` stays inside the graph)."""

    def __init__(self, t_vec, pos, ctx):
        super().__init__()
        self._t_vec = t_vec
        self._pos = pos
        self._ctx = ctx

    def __getitem__(self, index):
        return NDArray(self._t_vec[self._pos[index]], ctx=self._ctx)


def _constrain_like(value, sharding):
    """Pin a traced output (pytree) to the input arrays' NamedShardings so
    a donated update hands back buffers with the SAME layout (GSPMD would
    otherwise pick its own, silently re-laying-out TP/ZeRO-sharded
    tensors)."""
    import jax
    from jax.sharding import NamedSharding
    if value is None:
        return None
    if isinstance(value, (tuple, list)):
        return tuple(_constrain_like(v, s)
                     for v, s in zip(value, sharding))
    if isinstance(sharding, NamedSharding):
        return jax.lax.with_sharding_constraint(value, sharding)
    return value


def _sharding_tree(x):
    """Mirror an NDArray-state pytree with each leaf's current sharding."""
    if x is None:
        return None
    if isinstance(x, (tuple, list)):
        return tuple(_sharding_tree(v) for v in x)
    data = getattr(x, "_data", x)
    return getattr(data, "sharding", None)


def _apply_traced(opt, indices, ws, gs, ss, ctx, lr_vec, wd_vec, t_vec,
                  rescale):
    """Trace the PUBLIC optimizer over all parameters at once.

    Runs inside a jax trace: `opt`'s lr/wd/t/rescale lookups are patched to
    return traced scalars, then `update_multi_precision` is called per
    parameter on NDArray shells wrapping the traced arrays.  The patches
    are removed before returning (they only matter at trace time;
    compiled executions never re-enter this Python).
    """
    pos = {i: k for k, i in enumerate(indices)}
    saved = dict(vars(opt))
    try:
        opt._get_lr = lambda i: NDArray(lr_vec[pos[i]], ctx=ctx)
        opt._get_wd = lambda i: NDArray(wd_vec[pos[i]], ctx=ctx)
        opt._update_count = lambda i: None  # host-side, done by the caller
        opt._index_update_count = _TMap(t_vec, pos, ctx)
        opt.rescale_grad = NDArray(rescale, ctx=ctx)
        new_ws, new_ss = [], []
        for k, i in enumerate(indices):
            w = NDArray(ws[k], ctx=ctx)
            g = NDArray(gs[k], ctx=ctx)
            s = _state_wrap(ss[k], ctx)
            opt.update_multi_precision(i, w, g, s)
            new_ws.append(w._data)
            new_ss.append(_state_data(s))
        return new_ws, tuple(new_ss)
    finally:
        for k in list(vars(opt)):
            if k not in saved:
                delattr(opt, k)
        opt.__dict__.update(saved)


def reown_for_donation(tree):
    """Re-materialize every array leaf of `tree` through one jitted XLA
    copy, so the returned buffers are exclusively owned by this
    process's XLA computations.

    Why: a donated dispatch through an AOT executable (the unified
    program cache's `jit.lower().compile()` path, or an executable
    deserialized from the disk tier) silently corrupts buffers that
    came from `jax.device_put` of HOST memory — checkpoint restores,
    external `set_params`, epoch-boundary param syncs all stage arrays
    that way.  The plain `jax.jit` dispatch path defensively copies
    such inputs; the AOT call path does not, and XLA's in-place reuse
    of the donated buffer then races whatever still aliases the staged
    host copy (observed: nondeterministically wrong resumed-training
    params at ~30-50%, and glibc heap corruption for the in-process
    deserialize variant).  Fused steps call this on every COLD dispatch
    — the only time externally-staged buffers can enter the donated
    carry; the steady-state fast path (our own previous outputs) never
    pays it.  The copy is one fused program per signature (jax.jit's
    own cache), not a per-leaf dispatch."""
    import jax
    import jax.numpy as jnp

    def copy_leaf(x):
        if not hasattr(x, "dtype"):
            return x
        if x.dtype == jnp.bool_:
            return jnp.logical_or(x, False)
        # multiply by one: bitwise identity for every float/int/uint
        # dtype, and inside a non-donating jit the output is a FRESH
        # buffer (a bare identity could be forwarded/aliased by XLA)
        return x * jnp.ones((), x.dtype)

    global _REOWN_JIT
    if _REOWN_JIT is None:
        _REOWN_JIT = jax.jit(
            lambda t: jax.tree_util.tree_map(copy_leaf, t))
    return _REOWN_JIT(tree)


_REOWN_JIT = None


# NOTE on donation safety (formerly a _AotCall pre-validation wrapper):
# donation consumes the caller's persistent buffers only when the compiled
# executable actually RUNS — a failed trace or compile raises before
# execution with every buffer intact, and callers triage post-dispatch
# failures with _raise_if_unrecoverable (is_deleted on the inputs).  A
# `jit.lower(*args)` pre-validation pass would re-trace the whole
# multi-thousand-op graph and double first-step latency for no safety.


@contextlib.contextmanager
def _quiet_donation():
    """Warning scope for an auto-donating dispatch: jax warns when a
    donated buffer cannot alias any program output, and for donated
    batch INPUTS that is the common case (the step's outputs are small)
    — the donation still lets the runtime release the staged buffer at
    dispatch instead of holding it across the step.  Expected, not
    actionable; silence exactly that message."""
    import warnings
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def _maybe_scan_plan(symbol):
    """The symbol's scan-over-layers plan when MXNET_FUSED_SCAN is on and
    the graph has at least one eligible run, else None.  Never raises —
    a failed detection pass just means the inlined lowering."""
    from . import config as _config
    if not bool(_config.get("MXNET_FUSED_SCAN")):
        return None
    try:
        from .analysis.graph_passes import scan_plan
        plan = scan_plan(symbol)
        return plan if plan.get("runs") else None
    except Exception as e:
        _log.debug("scan-over-layers detection failed (%s); using the "
                   "inlined lowering", str(e)[:200])
        return None


def _donated_invalidated(*trees):
    """True when any jax-array leaf in the given pytrees was deleted by a
    donating dispatch (promoted into `analysis.donation.any_deleted`; kept
    as the historical name for callers of the probe)."""
    from .analysis import donation as _donation
    return _donation.any_deleted(*trees)


def _opt_param_names(opt, indices):
    """Best-effort human names for optimizer parameter indices (Module
    installs `idx2name`; the gluon Trainer installs `param_dict`) — the
    names the donation tracker and unrecoverable-failure errors report."""
    i2n = getattr(opt, "idx2name", None) or {}
    pd = getattr(opt, "param_dict", None) or {}
    out = []
    for i in indices:
        if i in i2n:
            out.append(str(i2n[i]))
        elif i in pd and getattr(pd[i], "name", None):
            out.append(str(pd[i].name))
        else:
            out.append(f"param[{i}]")
    return out


def _param_dict_mults(opt, indices):
    """Per-parameter lr/wd multipliers from the optimizer's param_dict
    (consulted FIRST by _get_lr/_get_wd — gluon Trainer populates it), as
    a hashable tuple for the hyper-vector cache key: freezing a layer
    mid-training via `param.lr_mult = 0` must invalidate the cache."""
    pd = getattr(opt, "param_dict", None) or {}
    if not pd:
        return ()
    return tuple(
        (getattr(pd[i], "lr_mult", None), getattr(pd[i], "wd_mult", None))
        if i in pd else None for i in indices)


def _raise_if_unrecoverable(kind, exc, named_trees):
    """Shared post-dispatch failure triage for every fused path: when the
    donating dispatch already consumed the persistent buffers, falling
    back would replay onto deleted arrays — raise an `MXNetError` NAMING
    the consumed parameters instead (analysis.donation).  `named_trees`
    is an iterable of (owner_name, pytree).  Returns when a fallback is
    safe (buffers intact)."""
    from .analysis import donation as _donation
    _donation.raise_if_consumed(kind, exc, named_trees)


def _no_rng():
    """Context forbidding host RNG draws during a fused trace: a key drawn
    at trace time would bake the SAME randomness into every compiled step."""
    import contextlib
    from . import random as _random

    @contextlib.contextmanager
    def guard():
        orig = _random.next_key

        def blocked():
            raise RuntimeError(
                "optimizer draws host RNG; not fusable (fall back)")

        _random.next_key = blocked
        try:
            yield
        finally:
            _random.next_key = orig
    return guard()


# ---------------------------------------------------------------------------
# once-traced cores: the expensive framework trace captured as a closed
# jaxpr, replayed cheaply by every program built over it (shared by the
# Module and Gluon fused steps)
# ---------------------------------------------------------------------------

class _TracedCore:
    """`core(inner, x, *extras) -> (new_inner, step_out)` traced ONCE under
    `make_jaxpr` (this runs the whole framework graph's Python); calling
    the instance replays the jaxpr in jaxpr-eval time, so the 1-step jit
    and each K-step scan body re-trace for pennies instead of re-running
    framework op dispatch."""

    def __init__(self, core, example_args, axis_env=None):
        import jax
        import time as _time
        flat, in_tree = jax.tree_util.tree_flatten(tuple(example_args))

        def flat_core(*leaves):
            return core(*jax.tree_util.tree_unflatten(in_tree, leaves))

        # axis_env binds mesh axis names for the pod fast path's core
        # (its jaxpr contains psum/pmean/pmin eqns over the dp axis and
        # is traced with SHARD-local input shapes; the shard_map wrapper
        # binds the axis for real at lowering time)
        t0 = _time.perf_counter()
        closed, out_shape = jax.make_jaxpr(
            flat_core, return_shape=True,
            axis_env=axis_env)(*flat)
        self.trace_s = _time.perf_counter() - t0
        self._closed = closed
        self._in_tree = in_tree
        self._out_tree = jax.tree_util.tree_structure(out_shape)
        self.out_shape = out_shape   # (inner, step_out) ShapeDtypeStructs
        self._graph_hash = None

    def num_eqns(self):
        """Total equation count of the traced step, recursing into
        nested jaxprs (scan/cond/pjit bodies) — the graph-size number
        the cold-start work scales with.  A scan-deduped graph counts
        ONE layer body where the inlined lowering counts N."""
        def subs(v):
            vals = v if isinstance(v, (tuple, list)) else (v,)
            out = []
            for x in vals:
                inner = getattr(x, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    out.append(inner)
                elif hasattr(x, "eqns"):
                    out.append(x)
            return out

        def count(jaxpr):
            n = len(jaxpr.eqns)
            for eqn in jaxpr.eqns:
                for v in eqn.params.values():
                    for sub in subs(v):
                        n += count(sub)
            return n

        return count(self._closed.jaxpr)

    @property
    def graph_hash(self):
        """Stable identity of the traced step for the program cache's
        disk tier (the jaxpr print with addresses scrubbed — shapes,
        dtypes, optimizer math and metric set are all in it)."""
        if self._graph_hash is None:
            from .compile import graph_hash_of_jaxpr
            self._graph_hash = graph_hash_of_jaxpr(self._closed)
        return self._graph_hash

    def __call__(self, *args):
        import jax
        from jax.extend.core import jaxpr_as_fun
        leaves, tree = jax.tree_util.tree_flatten(tuple(args))
        if tree != self._in_tree:
            raise TypeError("fused-core signature changed under trace")
        out = jaxpr_as_fun(self._closed)(*leaves)
        return jax.tree_util.tree_unflatten(self._out_tree, out)


def advance_hyper_rows(opt, indices, k, owner, placement):
    """Advance the optimizer's update counts k steps and collect the k
    per-step (lr_vec, wd_vec) device rows plus the rescale scalar.

    The per-parameter vectors are base * static multipliers, so they are
    re-uploaded only when the BASE values move (scheduler step,
    set_learning_rate, rescale change) — cached on `owner._hyper_base` /
    `owner._hyper_dev`.  The base is evaluated once PER STEP (counts
    advance between evaluations), so an lr schedule stepping mid-block
    still lands exact per-step rows.  Shared by the Module and Gluon
    fused steps."""
    import jax
    rows = []
    for _ in range(k):
        for i in indices:
            opt._update_count(i)
        sched = getattr(opt, "lr_scheduler", None)
        base_lr = sched(opt.num_update) if sched is not None else opt.lr
        base = (float(base_lr), float(opt.wd), float(opt.rescale_grad),
                tuple(sorted(getattr(opt, "lr_mult", {}).items())),
                tuple(sorted(getattr(opt, "wd_mult", {}).items())),
                _param_dict_mults(opt, indices))
        if getattr(owner, "_hyper_base", None) != base:
            lrs = [float(opt._get_lr(i)) for i in indices]
            wds = [float(opt._get_wd(i)) for i in indices]
            owner._hyper_dev = jax.device_put(
                [_np.asarray(lrs, _np.float32),
                 _np.asarray(wds, _np.float32),
                 _np.float32(opt.rescale_grad)], placement)
            owner._hyper_base = base
        rows.append((owner._hyper_dev[0], owner._hyper_dev[1]))
    return rows, owner._hyper_dev[2]


def create_states_on_device(opt, indices, weights_raw, ctx):
    """Create optimizer state for every (index, raw device array) pair in
    ONE compiled program — the public optimizer's create_state traced over
    NDArray shells, so fp32 masters are in-program casts and momenta are
    in-program zeros.  Returns a list of NDArray-state pytrees, or None
    when the optimizer's create_state cannot trace (caller falls back to
    its eager/host path).  On a remote device the per-parameter eager path
    costs a round trip per op; this costs one dispatch total."""
    import jax
    try:
        def create(ws_in):
            return tuple(
                _state_data(opt.create_state_multi_precision(
                    i, NDArray(w, ctx=ctx)))
                for i, w in zip(indices, ws_in))

        with _no_rng():
            vals = jax.jit(create)(list(weights_raw))
    except Exception as e:
        _log.warning("on-device optimizer-state creation unavailable (%s); "
                     "using the eager path", str(e)[:200])
        return None
    return [_state_wrap(v, ctx) for v in vals]


def _pod_bucket_psum(grads, axis, cap_bytes, extras=()):
    """Exchange every gradient in O(buckets) psum collectives: pack the
    (trace-time-static) gradient list into size-capped same-dtype
    buckets — the kvstore scheduler's planning rule AND priority order
    (reversed parameter order), applied INSIDE the step program —
    flatten-concat each bucket and exchange it in its OWN `lax.psum`
    bind over the dp axis.  Backward materializes the LAST layer's
    gradients first, so the first-planned bucket's all-reduce depends
    only on ITS layers' VJP chain: the scheduler starts that collective
    while earlier layers' backward is still computing — the
    dependency-engine overlap, expressed as dataflow instead of
    host-side async dispatch.  One extra psum carries the
    small per-shard partial sums (metric deltas, BN aux moments, the
    guardian's health bit).  Returns (summed grads, bucket plan, summed
    extras, psum binds actually dispatched — the extras fold into the
    first f32 bucket when one exists and otherwise cost one extra
    bind).  The psum of per-shard gradients is the reference kvstore's
    cross-device sum."""
    import jax
    import jax.numpy as jnp
    from .kvstore import plan_buckets
    sizes = [int(_np.prod(g.shape)) * g.dtype.itemsize if g.shape
             else g.dtype.itemsize for g in grads]
    # the kvstore scheduler's EXACT plan, including its priority order:
    # reversed parameter order, so the last layers' gradients — the ones
    # backward's VJP chain produces first — form the first buckets
    plan = plan_buckets(reversed(range(len(grads))), sizes,
                        [g.dtype for g in grads], cap_bytes)
    flats = []
    for bucket in plan:
        if len(bucket) == 1:
            flats.append(grads[bucket[0]])
        else:
            flats.append(jnp.concatenate(
                [grads[i].reshape(-1) for i in bucket]))
    # the extras (metric deltas, BN aux moments, the health bit — all
    # small) CONCAT into the first f32 bucket's payload rather than
    # riding as extra psum operands: XLA-CPU rendezvouses multi-operand
    # all-reduces per operand, so one fused operand is one barrier
    ex_flat = [jnp.asarray(e, jnp.float32).reshape(-1) for e in extras]
    ex_sizes = [int(e.shape[0]) for e in ex_flat]
    ex_host = next((k for k, f in enumerate(flats)
                    if f.dtype == jnp.float32), None)
    if ex_flat and ex_host is not None:
        host_shape = flats[ex_host].shape
        flats[ex_host] = jnp.concatenate(
            [flats[ex_host].reshape(-1)] + ex_flat)
    sflats = [jax.lax.psum(f, axis) for f in flats]
    if ex_flat and ex_host is not None:
        host = sflats[ex_host]
        n_own = int(host.shape[0]) - sum(ex_sizes)
        sextras, off = [], n_own
        for n in ex_sizes:
            sextras.append(jax.lax.dynamic_slice_in_dim(host, off, n))
            off += n
        sflats[ex_host] = jax.lax.dynamic_slice_in_dim(
            host, 0, n_own).reshape(host_shape)
        sextras = [s.reshape(e.shape).astype(e.dtype)
                   for s, e in zip(sextras, extras)]
    else:
        sextras = jax.lax.psum(tuple(extras), axis) if extras else ()
    out = list(grads)
    for flat, bucket in zip(sflats, plan):
        if len(bucket) == 1:
            out[bucket[0]] = flat
            continue
        off = 0
        for i in bucket:
            n = int(_np.prod(grads[i].shape)) if grads[i].shape else 1
            out[i] = jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(
                grads[i].shape)
            off += n
    n_psums = len(plan) + (1 if (ex_flat and ex_host is None) else 0)
    return out, plan, sextras, n_psums


def predict_pod_plan(shapes, dtypes=None, cap_bytes=None, extras=True,
                     dp=1):
    """Static mirror of the pod fast path's in-graph bucket plan — the
    plan-introspection hook mxcost uses: given the parameter shapes (and
    dtypes) a fused step would exchange, derive the same plan
    `_pod_bucket_psum` cuts (the shared `kvstore.plan_buckets` rule in
    reversed parameter order) and the resulting collective economy.
    ``extras=True`` models the bundled metric/aux/health payload, which
    folds into the first f32 bucket when one exists and otherwise costs
    one extra psum — exactly the trace-time behavior, so the returned
    ``collectives_per_step``/``bytes_per_step`` match what
    `FusedTrainStep.pod_stats` reports after tracing a step that
    carries extras (metrics/aux/health — the normal fit path; pass
    ``extras=False`` for a bare step)."""
    from .analysis import cost as _cost
    # cap_bytes=None resolves MXNET_KVSTORE_BUCKET_MB inside the
    # enumerator — ONE cap-resolution rule, shared with the kvstore
    return _cost.enumerate_collectives(
        shapes, dtypes=dtypes, dp=dp, cap_bytes=cap_bytes, extras=extras,
        name="pod-plan")


def _one_step_jit(traced, label="", call_fn=None, key_tag=None,
                  donate_inputs=False):
    """1-step program over a traced core; the inner carry is donated.
    Compiled through the unified program cache (compile/): a process
    that traced an identical core loads the executable from the disk
    tier instead of paying the XLA compile.  `call_fn` substitutes a
    wrapped core (the pod path's shard_map) while `traced` still
    provides the cache identity; `key_tag` disambiguates the wrapper.

    `donate_inputs=True` builds the auto-donation variant: the batch
    inputs ride as their OWN argument (donated) while the hyper rows
    (lr/wd[/gmul]) stay in the non-donated remainder — the caller
    proved via jaxpr liveness (analysis.cost.jaxpr_dying_inputs) that
    every input buffer dies inside the step, and re-owns the staged
    inputs first (reown_for_donation discipline), so XLA reuses the
    batch's HBM for activations instead of holding it live."""
    from .compile import cached_jit
    fn = call_fn if call_fn is not None else traced

    if donate_inputs:
        def step1d(inner, inputs, xrest, *extras):
            return fn(inner, (inputs,) + tuple(xrest), *extras)

        return cached_jit(step1d, donate_argnums=(0, 1),
                          graph_key=("step1d", key_tag, traced.graph_hash),
                          label=label or "fused/step1")

    def step1(inner, x, *extras):
        return fn(inner, x, *extras)

    return cached_jit(step1, donate_argnums=(0,),
                      graph_key=("step1", key_tag, traced.graph_hash),
                      label=label or "fused/step1")


def _scan_block_jit(traced, mcarry_index=None, label="", call_fn=None,
                    key_tag=None, donate_inputs=False):
    """K-step program: `lax.scan` of the traced core over K stacked
    per-step inputs.  Returns (new_inner, ys, mys, last): `ys` stacks
    every step's outputs (so callers can expose batch j's outputs to a
    batch-j callback), `mys` stacks the metric carry BEFORE each step
    when `mcarry_index` names its slot in the inner carry (entries
    C_{-1}..C_{K-2}; together with the final carry that is every
    per-step metric state — stacked as scan OUTPUTS, i.e. fresh
    buffers, because the inner carry itself is donated and its entry
    tuples are dead after the dispatch), and `last` is step K-1's
    outputs sliced IN-PROGRAM (no extra host dispatch for the common
    "latest outputs" read)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from .compile import cached_jit
    fn = call_fn if call_fn is not None else traced

    def _run(inner, xs_list, extras):
        xs = jax.tree_util.tree_map(lambda *vs: jnp.stack(vs), *xs_list)

        def body(inn, x):
            new_inn, out = fn(inn, x, *extras)
            y = (out, inn[mcarry_index]) if mcarry_index is not None \
                else (out, None)
            return new_inn, y

        new_inner, (ys, mys) = lax.scan(body, inner, xs)
        last = jax.tree_util.tree_map(lambda y: y[-1], ys)
        return new_inner, ys, mys, last

    if donate_inputs:
        # auto-donation variant: per-step batch inputs as their own
        # donated argument; hyper rows stay non-donated (see
        # _one_step_jit).  xs_inputs[j] pairs back with xs_rest[j].
        def stepkd(inner, xs_inputs, xs_rest, *extras):
            xs_list = tuple((inp,) + tuple(rest)
                            for inp, rest in zip(xs_inputs, xs_rest))
            return _run(inner, xs_list, extras)

        return cached_jit(stepkd, donate_argnums=(0, 1),
                          graph_key=("scan2d", mcarry_index, key_tag,
                                     traced.graph_hash),
                          label=label or "fused/scan")

    def stepk(inner, xs_list, *extras):
        return _run(inner, xs_list, extras)

    return cached_jit(stepk, donate_argnums=(0,),
                      graph_key=("scan2", mcarry_index, key_tag,
                                 traced.graph_hash),
                      label=label or "fused/scan")


class _BlockMetricView:
    """Per-logical-step metric exposure for a K-step fused block.

    A K-step scan applies the whole block before any callback fires, so
    a batch-j callback would otherwise observe block-FINAL metric totals
    — and a callback that resets the metric mid-burst (Speedometer
    auto_reset) would silently lose the rest of the block from its next
    window.  The scan stacks the metric carry BEFORE every step (`mys`
    from `_scan_block_jit`: C_{-1}..C_{K-2}, fresh scan outputs — the
    inner carry's own tuples are donated and dead); with the final
    carry C_{K-1} that is every per-step state.  `expose(j)` installs
    batch-j totals before the batch-j callback, reset-aware:

    the visible total must always equal host-materialized state plus the
    installed device tuple.  `A` tracks the cumulative carry already
    absorbed into host state (by a `get()` materialize) or discarded (by
    a `reset()`): an untouched metric gets the cumulative carry C_j - A;
    a touched one re-bases at the previous step (A = C_{j-1}) so only
    step j's delta lands on whatever the callback left behind.  All
    arithmetic is lazy device scalars — no host sync."""

    def __init__(self, metric_objs, prestep_carries, finals):
        self._metrics = list(metric_objs)
        self._pre = prestep_carries       # per metric (sum_K, num_K)
        self._finals = list(finals)       # per metric tuple: C_{K-1}
        self._k = None if prestep_carries is None else \
            len(finals) and int(prestep_carries[0][1].shape[0])
        self._installed = {}              # id(m) -> tuple we set
        self._absorbed = {}               # id(m) -> A (None = zero)

    def arm(self):
        """Record the dispatch-time install (block-final totals) so the
        first `expose` can tell 'untouched' from 'callback consumed'."""
        for m, f in zip(self._metrics, self._finals):
            self._installed[id(m)] = f

    def _after(self, mi, j):
        """Cumulative carry AFTER step j (C_j)."""
        if j + 1 >= self._k:
            return self._finals[mi]
        s_stack, n_stack = self._pre[mi]
        return (s_stack[j + 1], n_stack[j + 1])

    def _before(self, mi, j):
        """Cumulative carry BEFORE step j (C_{j-1}; j=0 -> block entry)."""
        s_stack, n_stack = self._pre[mi]
        return (s_stack[j], n_stack[j])

    def expose(self, j):
        if self._pre is None:
            return
        for mi, m in enumerate(self._metrics):
            if m._device_totals is not self._installed.get(id(m)):
                # a callback materialized (get) or reset the metric —
                # everything it consumed is accounted for in its host
                # state; only deltas past that point may land on device.
                # Mid-burst the consumed value was step j-1's install, so
                # re-base at C_{j-1}.  BEFORE the first expose the armed
                # value was the block-FINAL totals: a materialize
                # absorbed C_{K-1} (host totals nonzero -> re-base
                # there); a reset discarded everything (host zeroed ->
                # re-base at block entry)
                if j > 0:
                    self._absorbed[id(m)] = self._before(mi, j)
                elif getattr(m, "num_inst", 0) or \
                        getattr(m, "sum_metric", 0.0):
                    self._absorbed[id(m)] = self._finals[mi]
                else:
                    self._absorbed[id(m)] = self._before(mi, 0)
            a = self._absorbed.get(id(m))
            cur = self._after(mi, j)
            if a is not None:
                cur = (cur[0] - a[0], cur[1] - a[1])
            m._device_totals = cur
            self._installed[id(m)] = cur


# ---------------------------------------------------------------------------
# FusedOptimizer: all parameter updates in one donated program
# ---------------------------------------------------------------------------

class FusedOptimizer:
    """One-dispatch optimizer application for a fixed parameter set.

    Replaces N per-parameter update dispatches (reference
    `model.py _update_params` / `gluon/trainer.py _update`) with a single
    donated XLA program.  Weight and state buffers are donated — the
    caller's NDArrays are repointed to the new buffers in place.
    """

    def __init__(self, optimizer):
        self._opt = optimizer
        self._jit = None
        self._broken = False

    def _build(self):
        import jax
        opt = self._opt

        def step(ws, gs, ss, lr_vec, wd_vec, t_vec, rescale):
            new_ws, new_ss = _apply_traced(opt, self._call_indices, ws, gs,
                                           ss, self._call_ctx, lr_vec,
                                           wd_vec, t_vec, rescale)
            new_ws = [_constrain_like(w, s)
                      for w, s in zip(new_ws, self._call_w_shardings)]
            new_ss = tuple(_constrain_like(s, sh)
                           for s, sh in zip(new_ss, self._call_s_shardings))
            return new_ws, new_ss

        self._jit = jax.jit(step, donate_argnums=(0, 2))

    def _hyper(self, indices):
        """Advance host-side update counts and collect per-parameter
        hyperparameters for injection (exact scheduler semantics: the real
        `_update_count`/`_get_lr`/`_get_wd` run on the host every step)."""
        opt = self._opt
        for i in indices:
            opt._update_count(i)
        lrs = _np.asarray([opt._get_lr(i) for i in indices], _np.float32)
        wds = _np.asarray([opt._get_wd(i) for i in indices], _np.float32)
        ts = _np.asarray([opt._index_update_count[i] for i in indices],
                         _np.float32)
        rescale = _np.float32(opt.rescale_grad)
        return lrs, wds, ts, rescale

    def __call__(self, indices, weights, grads, states):
        """Apply updates for all (index, weight, grad, state) in one
        program; falls back to the eager per-parameter path if the
        optimizer cannot trace."""
        opt = self._opt
        if self._broken:
            for i, w, g, s in zip(indices, weights, grads, states):
                opt.update_multi_precision(i, w, g, s)
            return
        lrs, wds, ts, rescale = self._hyper(indices)
        if self._jit is None:
            self._build()
        ws = [w._data for w in weights]
        gs = [g._data for g in grads]
        ss = tuple(_state_data(s) for s in states)
        self._call_indices = list(indices)
        self._call_ctx = weights[0].context
        self._call_w_shardings = [getattr(w, "sharding", None) for w in ws]
        self._call_s_shardings = tuple(_sharding_tree(s) for s in states)
        from . import analysis as _analysis
        if _analysis.enabled():
            self._step_no = getattr(self, "_step_no", 0) + 1
            names = _opt_param_names(opt, self._call_indices)
            _analysis.donation.record(
                f"FusedOptimizer step {self._step_no}",
                list(zip(names, ws)) +
                [(n + ".state", s) for n, s in zip(names, ss)])
        # counts were already advanced; replay through the raw update on
        # fallback (not update_multi_precision, which would double-count)
        try:
            with _no_rng():
                new_ws, new_ss = self._jit(ws, gs, ss, lrs, wds, ts, rescale)
        except Exception as e:
            names = _opt_param_names(opt, self._call_indices)
            _raise_if_unrecoverable(
                "fused optimizer apply", e,
                list(zip(names, ws)) +
                [(n + ".state", s) for n, s in zip(names, ss)])
            self._broken = True
            _log.warning(
                "fused optimizer apply unavailable for %s (%s); using the "
                "per-parameter path", type(opt).__name__, str(e)[:200])
            saved = dict(vars(opt))
            try:
                opt._update_count = lambda i: None  # already counted above
                for i, w, g, s in zip(indices, weights, grads, states):
                    opt.update_multi_precision(i, w, g, s)
            finally:
                for k in list(vars(opt)):
                    if k not in saved:
                        delattr(opt, k)
                opt.__dict__.update(saved)
            return
        for w, nw in zip(weights, new_ws):
            w._set_data(nw)
        for s, ns in zip(states, new_ss):
            _state_write_back(s, ns)


# ---------------------------------------------------------------------------
# FusedTrainStep: Module's forward+backward+update(+metric) in one program
# ---------------------------------------------------------------------------

class FusedTrainStep:
    """The `Module.fit` hot loop as one donated XLA program — or, in block
    mode, K train steps as one `lax.scan` program per dispatch.

    Built by `Module.init_optimizer` when eligible (single-process kvstore,
    plain ``write`` grads, no module states).  Each call:

      host:   advance optimizer counts, gather lr/wd/t scalars
      device: ONE program = forward + vjp + optimizer (traced public
              object) + BN-aux update + metric accumulation + key split
              — times K when the fit loop hands over a block of batches

    Parameters, optimizer state, aux state, the metric accumulator and the
    RNG key are donated carries — steady-state training allocates nothing
    and dispatches once per batch (once per K batches in block mode).

    Why blocks: on a host whose dispatches serialize with the device (one
    remote chip behind a tunnel; also the common single-process case the
    reference attacks with bulk-exec segments,
    `src/executor/graph_executor.cc:1194-1316`), the per-step host Python
    adds 1:1 to wall time.  `lax.scan` over K stacked batches amortizes the
    dispatch plus all host-side bookkeeping across K steps, which is what
    lets the public `fit` loop match a hand-pipelined raw-JAX loop.

    The expensive part of building these programs is tracing the framework
    graph (Python op dispatch over the whole Symbol).  That trace runs ONCE
    into a closed jaxpr; the 1-step jit and every K-step scan body replay
    the jaxpr (cheap) instead of re-running framework Python, so adding
    block mode does not multiply trace time.
    """

    def __init__(self, module, updater):
        import jax
        self._mod = module
        self._updater = updater
        self._symbol = module._symbol
        self._opt = updater.optimizer
        self._contexts = module._context
        exec0 = module._exec_group.execs[0]
        self._exec0 = exec0

        self._arg_names = self._symbol.list_arguments()
        self._aux_names = self._symbol.list_auxiliary_states()
        self._param_names = [n for n in module._exec_group.param_names
                             if module._exec_group.grad_req.get(n) == "write"]
        input_names = (module._exec_group.data_names +
                       module._exec_group.label_names)
        self._input_names = input_names
        # "fixed" args: bound but not updated (grad_req null non-inputs)
        self._fixed_names = [n for n in self._arg_names
                             if n not in self._param_names and
                             n not in input_names]
        ndev = len(self._contexts)
        update_on_kv = bool(module._update_on_kvstore)
        self._indices = [i if (update_on_kv or ndev == 1) else i * ndev
                         for i in range(len(module._exec_group.param_names))]
        self._indices = [self._indices[module._exec_group.param_names.index(n)]
                         for n in self._param_names]

        # device mesh for multi-device data parallelism — composed
        # dp×tp×pp meshes accepted from Module (`mesh=` / MXNET_MESH
        # spec through parallel/mesh.py); default: every context on one
        # 'dp' axis.  The batch shards over the dp axis only; params/
        # state replicate over it, and tensors the user sharded over the
        # OTHER axes (TP/PP) keep their layout (`_collect_misplaced`
        # respects same-mesh NamedShardings, `_constrain_like` pins the
        # step outputs to the input layouts).
        devices = [c.jax_device for c in self._contexts]
        mesh = getattr(module, "_mesh", None)
        if mesh is None and len(devices) > 1:
            from .parallel.mesh import mesh_from_spec
            try:
                mesh = mesh_from_spec(devices=devices)
            except Exception as e:
                _log.warning("MXNET_MESH spec ignored (%s); using the 1-D "
                             "dp mesh", str(e)[:200])
                mesh = None
        if len(devices) > 1 or mesh is not None:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            from .parallel.mesh import dp_axis_of
            if mesh is None:
                mesh = Mesh(_np.array(devices), ("dp",))
            self._mesh = mesh
            self._dp_axis = dp_axis_of(mesh)
            self._dp_size = int(mesh.shape[self._dp_axis])
            self._data_sharding = NamedSharding(mesh, P(self._dp_axis))
            self._rep_sharding = NamedSharding(mesh, P())
        else:
            from jax.sharding import SingleDeviceSharding
            self._mesh = None
            self._dp_axis = None
            self._dp_size = 1
            self._data_sharding = SingleDeviceSharding(devices[0])
            self._rep_sharding = SingleDeviceSharding(devices[0])
        # ZeRO-style weight-update sharding (MXNET_ZERO): optimizer-state
        # tensors lay out sharded over dp, so GSPMD lowers the gradient
        # exchange feeding the update to reduce-scatter, runs the
        # optimizer on the local 1/N shard only, and all-gathers the new
        # weights — the MLPerf-pods paper's weight-update sharding, via
        # sharding annotations instead of hand-written collectives
        # (parallel/zero.py holds the explicit shard_map machinery).
        from . import config as _config
        self._zero = bool(_config.get("MXNET_ZERO")) and \
            self._mesh is not None and self._dp_size > 1

        from .symbol.symbol import graph_eval_fn
        # scan-over-layers (MXNET_FUSED_SCAN): runs of structurally
        # identical blocks lower to ONE lax.scan body over stacked
        # per-layer params instead of N inlined copies — the jaxpr (and
        # so the unified program cache key, via graph_hash_of_jaxpr)
        # shrinks to one layer body; XLA compiles the layer once
        self._scan_plan = _maybe_scan_plan(self._symbol)
        self.scan_runs = [] if self._scan_plan is None else \
            [(r["name"], r["length"]) for r in self._scan_plan["runs"]]
        self._gfn, _, _, self._n_rng = graph_eval_fn(
            self._symbol, True, scan=self._scan_plan)
        # pod SPMD fast path (MXNET_POD_SPMD): run the WHOLE step core
        # inside shard_map over the dp axis with a bucketed single-psum
        # gradient exchange.  The GSPMD global-view lowering inserts one
        # all-reduce per gradient tensor at its producing dot; on a wide
        # mesh every collective is a cross-device barrier, so O(params)
        # barriers per step amplify per-partition skew.  The pod path
        # exchanges ALL gradients in O(buckets) collectives
        # (MXNET_KVSTORE_BUCKET_MB caps a bucket — the same knob and
        # planning rule as the kvstore scheduler), which benches ~1.2x
        # faster per step on the 8-way mesh.  Semantics: the psum of
        # per-shard gradients is exactly the reference kvstore's
        # cross-device SUM (comm.h Reduce), so sum-normalized graphs
        # (normalization='null') match the global-view program bit-for-
        # bit in structure; batch-normalized losses keep their classic
        # per-device normalization, as on the reference engine.
        self._pod_axis = None
        self.pod_stats = None
        if self._dp_size > 1 and not self._zero and \
                bool(_config.get("MXNET_POD_SPMD")) and \
                self._mesh is not None and \
                all(int(self._mesh.shape[a]) == 1
                    for a in self._mesh.axis_names
                    if a != self._dp_axis) and \
                self._pod_graph_ok():
            self._pod_axis = self._dp_axis
        self._key = None
        self._jit = None          # 1-step program
        self._jit_block = {}      # K -> K-step scan program
        self._core_closed = None  # the once-traced step jaxpr
        self._core_sig = None     # input signature the core was traced for
        self._core_cache = {}     # in_sig -> traced program set (retrace
                                  # survival for alternating signatures)
        self._autodonate_on = False  # per-core liveness decision (see
                                     # _decide_autodonate)
        self._derive_fn = None    # masters -> low-precision weights (flush)
        self.last_outputs = None
        self._block_outs = None   # scan ys: per-batch outputs of a block
        self.broken = False
        self._carry = None  # steady-state fast-path cache (see _dispatch)
        self._block_view = None  # per-step metric exposure for bursts
        self._derive_ws = False  # set by _build_core (see _master_positions)
        self._guardian = None    # resilience.guardian.TrainingGuardian
        self._guard = False      # in-graph health word armed (see below)
        FusedTrainStep._seq = getattr(FusedTrainStep, "_seq", 0) + 1
        self._audit_key = f"FusedTrainStep#{FusedTrainStep._seq}"
        self._step_no = 0   # donation-tracker step counter

    def attach_guardian(self, guardian):
        """Arm (or disarm, with None) the training guardian's in-graph
        health word: the step core gains an all-finite + gradient-norm
        reduction and a conditional update (a non-finite step's weight/
        state/aux/metric updates are `where`-selected away while RNG key
        and update counts advance — the deterministic skip-batch path).
        Flipping the armed state drops the traced cores so the next
        dispatch rebuilds with (or without) the health machinery."""
        armed = guardian is not None and getattr(guardian, "in_graph",
                                                 True)
        self._guardian = guardian
        if armed != self._guard:
            self._guard = armed
            self._core_closed = None
            self._core_cache = {}
            self._carry = None
            self._t_vec = None

    # -- placement of persistent buffers -------------------------------------
    # Every call normalizes buffer shardings (a no-op once placed): other
    # code paths — set_params at epoch boundaries, checkpoint loads — may
    # legally repoint these NDArrays at single-device arrays between steps.
    def _collect_misplaced(self, a, out, target=None):
        from jax.sharding import NamedSharding
        target = target if target is not None else self._rep_sharding
        cur = getattr(a._data, "sharding", None)
        if cur == target:
            return
        if target is self._rep_sharding and self._mesh is not None and \
                self._pod_axis is None and \
                isinstance(cur, NamedSharding) and cur.mesh == self._mesh:
            # user-sharded on the fused mesh (TP/PP axes): keep the layout
            # (the pod fast path instead REQUIRES replicated carries — its
            # shard_map in_specs claim P() — so it never takes this branch)
            return
        out.append((a, target))

    def _pod_graph_ok(self):
        """Graph eligibility for the pod shard_map fast path.  Fall back
        to the GSPMD lowering when the program samples RNG (per-shard
        streams would diverge from the global-view program), when a
        SoftmaxOutput normalizes by batch/valid (its scale would bake the
        SHARD batch size into the traced graph), when a train-mode
        BatchNorm is NOT sync=True (the fused global-view program
        computes GLOBAL-batch moments — that is this framework's
        documented BatchNorm semantics — but inside shard_map a plain
        mean reduces over the SHARD batch; sync BN psums the moments so
        it keeps the global statistics on either lowering), or when an
        aux state is non-floating (aux updates are pmean-averaged across
        shards — the reference executor group's cross-device aux
        averaging)."""
        if self._n_rng:
            return False
        try:
            import json as _json
            g = _json.loads(self._symbol.tojson())
            for node in g.get("nodes", []):
                attrs = node.get("attrs") or {}
                if node.get("op") in ("SoftmaxOutput", "Softmax") and \
                        attrs.get("normalization", "null") != "null":
                    return False
                if node.get("op") in ("BatchNorm", "BatchNorm_v1") and \
                        str(attrs.get("use_global_stats", "False")
                            ).lower() not in ("true", "1"):
                    if str(attrs.get("sync", "False")).lower() not in \
                            ("true", "1"):
                        return False
                    if str(attrs.get("sync_axis", "dp")) != self._dp_axis:
                        # sync BN psums over its `sync_axis` NAME; on a
                        # mesh whose dp axis is named differently the
                        # in-op axis probe would silently fail and the
                        # moments would go shard-local — fall back to
                        # the global-view lowering, which computes
                        # global-batch moments regardless of axis names
                        return False
        except Exception:
            return False
        try:
            import jax.numpy as jnp
            for n in self._aux_names:
                if not jnp.issubdtype(
                        self._exec0.aux_dict[n].dtype, jnp.floating):
                    return False
        except Exception:
            return False
        return True

    def _zero_sharding(self, a):
        """Dim-0-over-dp NamedSharding for a ZeRO-eligible optimizer
        state tensor (dim0 divides the dp axis), else replicated.
        Scalars and ragged tensors stay replicated — the big tensors
        carry virtually all the optimizer-state bytes."""
        if not self._zero:
            return self._rep_sharding
        shape = tuple(a.shape)
        if not shape or shape[0] % self._dp_size:
            return self._rep_sharding
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(
            self._mesh,
            P(*((self._dp_axis,) + (None,) * (len(shape) - 1))))

    def _place_state(self, s, out):
        if isinstance(s, NDArray):
            self._collect_misplaced(s, out, self._zero_sharding(s))
        elif isinstance(s, (tuple, list)):
            for x in s:
                self._place_state(x, out)

    def _place_all(self):
        import jax
        exec0 = self._exec0
        upd = self._updater
        need = [(i, n) for i, n in zip(self._indices, self._param_names)
                if i not in upd.states]
        if need:
            self._create_states(need)
        todo = []
        for n in self._param_names + self._fixed_names:
            self._collect_misplaced(exec0.arg_dict[n], todo)
        for n in self._aux_names:
            self._collect_misplaced(exec0.aux_dict[n], todo)
        for i in self._indices:
            self._place_state(upd.states[i], todo)
        if todo:
            # ONE batched transfer instead of a round trip per array
            # (per-leaf target shardings: replicated, or dp-sharded for
            # ZeRO-eligible optimizer state)
            moved = jax.device_put([a._data for a, _ in todo],
                                   [t for _, t in todo])
            for (a, _), v in zip(todo, moved):
                a._set_data(v)

    def _create_states(self, need):
        """All missing optimizer states in ONE compiled program from the
        device-resident weights (masters are casts, the rest zeros): no
        per-parameter dispatches, no weight download, no state upload —
        on a remote device the old fetch-create-upload path cost seconds
        of round trips.  Falls back to the host-staged path when the
        optimizer's create_state cannot trace."""
        exec0 = self._exec0
        upd = self._updater
        ctx = self._contexts[0]
        indices = [i for i, _ in need]
        ws = [exec0.arg_dict[n]._data for _, n in need]
        states = create_states_on_device(self._opt, indices, ws, ctx)
        if states is None:
            self._create_states_host(need)
            return
        for (i, _), s in zip(need, states):
            upd.states[i] = s
            upd.states_synced[i] = True

    def _create_states_host(self, need):
        """Host-staged fallback: ONE batched weight read, create_state on
        staged shells under a bulk scope, one batched upload (done by the
        placement pass that follows)."""
        import jax
        from . import engine as _engine
        exec0 = self._exec0
        upd = self._updater
        host_ws = jax.device_get(
            [exec0.arg_dict[n]._data for _, n in need])
        with _engine.bulk(1 << 16):
            for (i, n), hw in zip(need, host_ws):
                tgt = exec0.arg_dict[n]
                shell = NDArray(_np.asarray(hw), ctx=tgt.context)
                _engine.stage(shell)
                upd.states[i] = self._opt.create_state_multi_precision(
                    i, shell)
                upd.states_synced[i] = True
                _engine.unstage(shell)  # scratch; never uploaded

    # -- derived low-precision weights ---------------------------------------
    def _master_positions(self):
        """For every trainable param, the leaf index of its fp32 master in
        the optimizer-state pytree — or None when any param lacks one.

        When every weight has a master (bf16/fp16 multi-precision
        training), the low-precision weights need not be dispatch
        arguments at all: the program derives them from the masters at
        entry (one cast XLA fuses into the first consumer), dropping
        n_params input leaves + donation aliases from every step."""
        import jax
        exec0 = self._exec0
        upd = self._updater
        pos = []
        probed = {}   # state-structure key -> master leaf index (or None)
        for i, n in zip(self._indices, self._param_names):
            w = exec0.arg_dict[n]
            if _np.dtype(w.dtype) == _np.float32:
                return None
            leaves = jax.tree_util.tree_leaves(
                _state_data(upd.states.get(i)))
            cands = [j for j, lf in enumerate(leaves)
                     if str(getattr(lf, "dtype", "")) == "float32"
                     and tuple(getattr(lf, "shape", ())) == tuple(w.shape)]
            if len(cands) == 1:
                pos.append(cands[0])
                continue
            if not cands:
                return None
            # ambiguous (e.g. adam/sgd-momentum: momentum and master are
            # both fp32 of the weight's shape): probe the optimizer's
            # state STRUCTURE with a tiny nonzero weight and find the leaf
            # equal to its fp32 copy.  The structure is a property of the
            # optimizer, not of the individual parameter, so one probe per
            # distinct (dtype, leaf-structure) serves all 100+ params —
            # and it runs on the HOST backend (w.context may sit behind a
            # network tunnel where per-param probing costs a round trip
            # each).
            key = (str(_np.dtype(w.dtype)), tuple(cands),
                   tuple(str(getattr(lf, "dtype", "")) for lf in leaves))
            if key not in probed:
                from .ndarray.ndarray import array as _arr
                from .context import cpu as _cpu
                tw = _arr(_np.linspace(0.1, 0.9, 4, dtype=_np.float32),
                          ctx=_cpu(), dtype=w.dtype)
                ps = self._opt.create_state_multi_precision(i, tw)
                pl = jax.tree_util.tree_leaves(_state_data(ps))
                host = jax.device_get([tw._data] + [
                    pl[j] for j in cands if j < len(pl)])
                target = _np.asarray(host[0], _np.float32)
                hit = [j for j, hv in zip(
                    [c for c in cands if c < len(pl)], host[1:])
                    if _np.array_equal(_np.asarray(hv, _np.float32), target)]
                probed[key] = hit[0] if len(hit) == 1 else None
            if probed[key] is None:
                return None
            pos.append(probed[key])
        return pos

    # -- the traced step core ------------------------------------------------
    def _build_core(self, metric_fns):
        """The one-step train function over raw arrays.  Returned as plain
        Python; `_trace_core` runs it exactly once under `make_jaxpr`."""
        import jax
        import jax.numpy as jnp

        gfn = self._gfn
        arg_names = self._arg_names
        param_pos = {n: k for k, n in enumerate(self._param_names)}
        input_pos = {n: k for k, n in enumerate(self._input_names)}
        fixed_pos = {n: k for k, n in enumerate(self._fixed_names)}
        n_label = len(self._mod._exec_group.label_names)
        opt = self._opt
        indices = self._indices
        ctx = self._contexts[0]
        n_rng = self._n_rng
        mp_pos = self._master_positions()
        self._derive_ws = mp_pos is not None and len(mp_pos) > 0
        self._mp_pos = mp_pos
        self._w_dtypes = [self._exec0.arg_dict[n].dtype
                          for n in self._param_names]
        derive = self._derive_ws
        w_dtypes = self._w_dtypes
        guard = self._guard
        pod_axis = self._pod_axis
        pod_dp = self._dp_size
        if pod_axis is not None:
            from . import config as _config
            pod_cap = max(1, int(float(_config.get(
                "MXNET_KVSTORE_BUCKET_MB")) * (1 << 20)))
        else:
            pod_cap = None

        def core(inner, x, fixed, rescale):
            ws, ss, auxs, mcarry, key, t_vec = inner
            if guard:
                # gmul: the guardian's per-step gradient multiplier (1.0
                # in production; NaN / spike-scale under fault injection)
                inputs, lr_vec, wd_vec, gmul = x
            else:
                inputs, lr_vec, wd_vec = x
            if derive:
                ws = [jax.tree_util.tree_leaves(s)[p].astype(dt)
                      for s, p, dt in zip(ss, mp_pos, w_dtypes)]
            # t advances IN-GRAPH (donated carry): the host passes the
            # update counts once when (re)arming and never re-uploads the
            # vector — keeping every steady-state dispatch argument a
            # device array so the C++ fast dispatch path engages
            t_vec = t_vec + jnp.float32(1.0)
            if n_rng:
                key, sub = jax.random.split(key)
            else:
                sub = key

            def forward(pws):
                args = []
                for n in arg_names:
                    if n in param_pos:
                        args.append(pws[param_pos[n]])
                    elif n in input_pos:
                        args.append(inputs[input_pos[n]])
                    else:
                        args.append(fixed[fixed_pos[n]])
                outs, new_aux = gfn(tuple(args), tuple(auxs), sub)
                return tuple(outs), tuple(new_aux)

            outs, vjp, new_aux = jax.vjp(forward, list(ws), has_aux=True)
            # scan carries must keep invariant dtypes (see gluon core): pin
            # aux updates to the stored aux dtype
            new_aux = tuple(
                na.astype(a.dtype) if na.dtype != a.dtype else na
                for na, a in zip(new_aux, auxs))
            cts = tuple(
                jnp.ones(o.shape, o.dtype)
                if jnp.issubdtype(o.dtype, jnp.floating)
                else jnp.zeros(o.shape, o.dtype) for o in outs)
            (grads,) = vjp(cts)
            if guard:
                grads = [g * jnp.asarray(gmul, g.dtype) for g in grads]
            pod_deltas = pod_outs_bad = None
            if pod_axis is not None:
                # the pod fast path's gradient exchange: every gradient
                # bucket, every metric delta, the BN aux moments and the
                # guardian's local-health bit ride ONE psum bind — a
                # single cross-device barrier per step.  Downstream
                # (update, guardian, optimizer state) runs on globally
                # identical values, replicated across the shards.
                labels_p = inputs[len(inputs) - n_label:] if n_label \
                    else ()
                extras = []
                for fn, _m in metric_fns:
                    dsum, dnum = fn(list(labels_p), list(outs))
                    # dnum rides the float bundle; counts are exact in
                    # f32 well past any step's sample count
                    extras.append(jnp.asarray(dsum, jnp.float32))
                    extras.append(jnp.asarray(dnum, jnp.float32))
                n_metric = len(metric_fns)
                extras.extend(list(new_aux))
                if guard:
                    oks = [jnp.isfinite(o).all() for o in outs
                           if jnp.issubdtype(o.dtype, jnp.floating)]
                    bad = jnp.float32(len(oks)) - sum(
                        (o.astype(jnp.float32) for o in oks),
                        jnp.float32(0.0))
                    extras.append(bad)
                grads, plan, sext, n_psums = _pod_bucket_psum(
                    grads, pod_axis, pod_cap, extras)
                self._pod_plan = plan
                self._pod_psums = n_psums
                pod_deltas = [(sext[2 * j], sext[2 * j + 1])
                              for j in range(n_metric)]
                # aux updates (BN moments) are averaged across shards —
                # the reference executor group's cross-device aux merge
                a0 = 2 * n_metric
                new_aux = tuple(
                    (sext[a0 + j] / jnp.asarray(pod_dp, na.dtype))
                    .astype(na.dtype)
                    for j, na in enumerate(new_aux))
                if guard:
                    pod_outs_bad = sext[-1]
            new_ws, new_ss = _apply_traced(opt, indices, ws, grads, ss, ctx,
                                           lr_vec, wd_vec, t_vec, rescale)
            if guard:
                # the health word, computed where the data lives: one
                # all-finite reduction over grads + floating outputs +
                # the applied update, and the spike detector's signal —
                # the parameter-DISPLACEMENT ratio ||new_w - w|| / ||w||.
                # (A gradient norm is a poor damage proxy: a wrecked
                # model can saturate into normal-looking gradients, and
                # a converged model's gradient noise spans decades.  The
                # displacement ratio measures the damage itself.)
                parts = [jnp.isfinite(g).all() for g in grads]
                if pod_axis is not None:
                    # the shard-local output check already crossed the
                    # wire inside the bundled exchange: a shard whose
                    # LOCAL outputs went non-finite refuses the step on
                    # every shard (grads/new_ws are globally identical
                    # post-exchange, so those checks need no wire)
                    parts.append(pod_outs_bad <= jnp.float32(0.5))
                else:
                    parts += [jnp.isfinite(o).all() for o in outs
                              if jnp.issubdtype(o.dtype, jnp.floating)]
                parts += [jnp.isfinite(nw).all() for nw in new_ws]
                finite = parts[0]
                for p in parts[1:]:
                    finite = jnp.logical_and(finite, p)
                unorm2 = sum(
                    jnp.sum(jnp.square(nw.astype(jnp.float32)
                                       - w.astype(jnp.float32)))
                    for nw, w in zip(new_ws, ws))
                wnorm2 = sum(
                    jnp.sum(jnp.square(w.astype(jnp.float32)))
                    for w in ws)
                signal = jnp.sqrt(unorm2) / (jnp.sqrt(wnorm2)
                                             + jnp.float32(1e-12))
                # skip-batch: a non-finite step's updates are refused IN
                # THE PROGRAM — weights/optimizer state/aux keep their
                # input values; RNG key and update counts still advance,
                # so a skipped step is deterministic and reproducible
                def keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(finite, n,
                                               o.astype(n.dtype)),
                        new, old)

                new_ws = [jnp.where(finite, nw, w.astype(nw.dtype))
                          for nw, w in zip(new_ws, ws)]
                new_ss = tuple(keep(ns, s) for ns, s in zip(new_ss, ss))
            if guard:
                # BN aux updated by a non-finite forward is refused too
                new_aux = tuple(
                    jnp.where(finite, na, a.astype(na.dtype))
                    for na, a in zip(new_aux, auxs))
            # keep the persistent carries in their input layout (replicated
            # for DP; whatever the user sharded for TP/ZeRO).  Inside the
            # pod shard_map the layout is enforced by the out_specs
            # instead — sharding constraints are global-view constructs.
            if pod_axis is None:
                new_ss = tuple(
                    _constrain_like(s, sh)
                    for s, sh in zip(new_ss, self._call_s_shardings))
                new_aux = tuple(
                    _constrain_like(a, s)
                    for a, s in zip(new_aux, self._call_a_shardings))
            if derive:
                new_ws = ()   # flush re-derives from the masters on demand
            elif pod_axis is None:
                new_ws = tuple(
                    _constrain_like(w, s)
                    for w, s in zip(new_ws, self._call_w_shardings))
            else:
                new_ws = tuple(new_ws)
            labels = inputs[len(inputs) - n_label:] if n_label else ()
            new_mcarry = []
            for j, ((fn, _), (msum, mnum)) in enumerate(
                    zip(metric_fns, mcarry)):
                if pod_deltas is not None:
                    # global deltas arrived inside the bundled exchange
                    dsum, dnum = pod_deltas[j]
                    dnum = dnum.astype(jnp.int32)
                else:
                    dsum, dnum = fn(list(labels), list(outs))
                    dsum = jnp.asarray(dsum, jnp.float32)
                    dnum = jnp.asarray(dnum, jnp.int32)
                if guard:
                    # a skipped batch must not poison the metric totals
                    dsum = jnp.where(finite, dsum, jnp.zeros_like(dsum))
                    dnum = jnp.where(finite, dnum, jnp.zeros_like(dnum))
                # counts carry as int32: float32 would silently stop
                # incrementing past 2^24 samples
                new_mcarry.append((msum + dsum, mnum + dnum))
            new_inner = (new_ws, new_ss, tuple(new_aux), tuple(new_mcarry),
                         key, t_vec)
            if guard:
                # per-step health word: fetched asynchronously by the
                # guardian (device scalars; no host sync on this path)
                return new_inner, (tuple(outs),
                                   (finite.astype(jnp.float32), signal))
            return new_inner, tuple(outs)

        return core

    def _trace_core(self, core, example):
        """Run the framework trace ONCE; every program replays the jaxpr.
        In pod mode the trace runs with SHARD-local input shapes under
        the dp axis env — the jaxpr replays inside the shard_map wrap."""
        if self._pod_axis is not None:
            example = self._pod_shrink(example)
            self._pod_example = example
            self._core_closed = _TracedCore(
                core, example,
                axis_env=[(self._pod_axis, self._dp_size)])
        else:
            self._core_closed = _TracedCore(core, example)

    # -- pod fast-path plumbing ----------------------------------------------
    def _pod_shrink(self, example):
        """The trace example with every data/label input shrunk to its
        per-shard shape (ShapeDtypeStructs; carries stay global — they
        are replicated, so local == global)."""
        import jax
        inner, x, fixed, rescale = example
        dp = self._dp_size

        def shrink(v):
            s = tuple(v.shape)
            return jax.ShapeDtypeStruct((s[0] // dp,) + s[1:], v.dtype)

        inputs = tuple(shrink(v) for v in x[0])
        return (inner, (inputs,) + tuple(x[1:]), fixed, rescale)

    def _pod_outs_ok(self):
        """Every graph output must be batch-led (its shard_map out_spec
        stitches the per-shard rows back into the global batch); a
        scalar/reduced output has no general reconstitution rule."""
        inner, x, *_ = self._pod_example
        local_b = x[0][0].shape[0]
        step_out = self._core_closed.out_shape[1]
        outs = step_out[0] if self._guard else step_out
        import jax
        return all(
            getattr(o, "shape", ()) and o.shape[0] == local_b
            for o in jax.tree_util.tree_leaves(outs))

    def _pod_call(self):
        """The shard_map-wrapped core (or None outside pod mode): batch
        inputs and graph outputs shard over the dp axis, every carry is
        replicated."""
        if self._pod_axis is None:
            return None
        import jax
        from jax.sharding import PartitionSpec as P
        from .parallel.mesh import compat_shard_map
        axis = self._pod_axis
        tmap = jax.tree_util.tree_map
        rep = lambda t: tmap(lambda _: P(), t)                # noqa: E731
        shd = lambda t: tmap(lambda _: P(axis), t)            # noqa: E731
        inner_ex, x_ex, fixed_ex, rescale_ex = self._pod_example
        x_spec = (shd(x_ex[0]),) + tuple(rep(e) for e in x_ex[1:])
        in_specs = (rep(inner_ex), x_spec, rep(fixed_ex), P())
        new_inner_sh, step_out_sh = self._core_closed.out_shape
        if self._guard:
            out_specs = (rep(new_inner_sh),
                         (shd(step_out_sh[0]), rep(step_out_sh[1])))
        else:
            out_specs = (rep(new_inner_sh), shd(step_out_sh))
        return compat_shard_map(self._core_closed, mesh=self._mesh,
                                in_specs=in_specs, out_specs=out_specs)

    def _pod_tag(self):
        return None if self._pod_axis is None else \
            ("pod", self._pod_axis, self._dp_size)

    def _build1(self):
        self._jit = _one_step_jit(self._core_closed, label=self._audit_key,
                                  call_fn=self._pod_call(),
                                  key_tag=self._pod_tag(),
                                  donate_inputs=self._autodonate_on)

    def _buildk(self, k):
        # one scan-jit serves every K (xs arity keys the jit's own cache);
        # the per-K dict entry is the "this block size has run" record.
        # mcarry_index=3: the metric accumulator's slot in the inner
        # carry — the scan stacks it per step for the callback burst
        jitk = self._scan_jit if getattr(self, "_scan_jit", None) is not None \
            else _scan_block_jit(self._core_closed, mcarry_index=3,
                                 label=self._audit_key,
                                 call_fn=self._pod_call(),
                                 key_tag=self._pod_tag(),
                                 donate_inputs=self._autodonate_on)
        self._scan_jit = jitk
        self._jit_block[k] = jitk
        return jitk

    def _decide_autodonate(self, inner, x0):
        """Trace-time auto-donation decision (MXNET_FUSED_AUTODONATE):
        donate the staged batch inputs iff EVERY input leaf provably
        dies inside the traced step — its invar never reaches the core
        jaxpr's outvars (analysis.cost.jaxpr_dying_inputs).  A graph
        that echoes an input into its heads keeps the buffer live in
        `last_outputs`, so donation stays off for the whole input set.
        The dispatch re-owns staged inputs before a donating call
        (reown_for_donation): staged arrays can be device_put of HOST
        memory or adopted caller-owned arrays (prestage/io ring), both
        unsafe to donate raw."""
        from . import config as _config
        if not bool(_config.get("MXNET_FUSED_AUTODONATE")):
            return False
        try:
            import jax
            from .analysis import cost as _cost
            n_inner = len(jax.tree_util.tree_leaves(inner))
            n_inputs = len(jax.tree_util.tree_leaves(tuple(x0[0])))
            if not n_inputs:
                return False
            idx = list(range(n_inner, n_inner + n_inputs))
            dying = _cost.jaxpr_dying_inputs(self._core_closed._closed,
                                             idx)
            return len(dying) == n_inputs
        except Exception as e:
            _log.debug("auto-donation liveness analysis failed (%s); "
                       "keeping inputs undonated", str(e)[:200])
            return False

    # -- per-call ------------------------------------------------------------
    def _metric_leaves(self, eval_metric):
        """Leaf metrics with device-side update fns, or None when any leaf
        cannot run in-graph (caller then uses the host update path)."""
        from . import metric as _metric
        if eval_metric is None:
            return []
        if isinstance(eval_metric, _metric.CompositeEvalMetric):
            leaves = eval_metric.metrics
        else:
            leaves = [eval_metric]
        out = []
        for m in leaves:
            fn = getattr(m, "device_update", None)
            if fn is None:
                return None
            out.append((fn, m))
        return out

    def __call__(self, data_batch, eval_metric=None):
        """Run one fused train step.  Returns True when handled (metric
        included); False -> caller must use the unfused path."""
        return self._dispatch([data_batch], eval_metric)

    def call_block(self, batches, eval_metric=None):
        """Run len(batches) train steps as ONE `lax.scan` dispatch.
        Returns True when handled; False -> caller runs them one by one."""
        return self._dispatch(list(batches), eval_metric)

    def _batch_sig(self, batches):
        sig = None
        for b in batches:
            s = tuple((getattr(v, "shape", None), getattr(v, "dtype", None))
                      for v in list(b.data) + list(b.label or []))
            if sig is None:
                sig = s
            elif s != sig:
                return None   # mixed shapes cannot share one program
        return sig

    def _dispatch(self, batches, eval_metric):
        if self.broken:
            return False
        import jax
        mod = self._mod
        k = len(batches)

        metric_fns = self._metric_leaves(eval_metric)
        if metric_fns is None:
            self.flush()
            return False
        in_sig = self._batch_sig(batches)
        if in_sig is None:
            self.flush()
            return False
        from . import analysis as _analysis
        # steady-state fast path: when every persistent buffer is still the
        # array WE wrote back last step (verified by identity), placement,
        # sharding collection and signature validation are all known-good
        # and skipped — per-step host work drops to the hyper scalars and
        # the dispatch itself
        carry = self._carry if getattr(self, "_carry", None) else None
        exec0 = self._exec0
        if carry is not None:
            # load_optimizer_states swaps the whole states dict — identity
            # of the dict covers external state replacement; the input
            # signature must also match (a new batch shape needs the full
            # validation path before the donating dispatch).  The exec
            # buffers are compared against what WE last physically wrote
            # (`_seen_*`): in steady state write-backs are deferred (see
            # flush()), so the dicts still hold the last-flushed arrays.
            ok = getattr(self, "_carry_sdict", None) is \
                self._updater.states and \
                in_sig == getattr(self, "_carry_in_sig", None) and \
                self._owns_exec_buffers() and \
                all(exec0.aux_dict[n]._data is a
                    for n, a in zip(self._aux_names, self._seen_aux))
            if not ok:
                carry = None
        # a metric change forces the cold path too — decide BEFORE the
        # flush block, which must run whenever the cold path will read the
        # exec-dict arrays (in steady state they were donated last step);
        # the build itself runs AFTER placement (it probes the optimizer
        # states _place_all creates)
        need_build = self._core_closed is None or \
            metric_fns_changed(self._metric_sig(), metric_fns)
        if need_build:
            self._metric_ids = [id(m) for _, m in metric_fns]
            self._core_closed = None   # metric set is baked into the core
            self._core_cache = {}      # shapes AND metrics key the cores
            carry = None
        if carry is None:
            if self._owns_exec_buffers():
                self.flush()
            else:
                # an external writer repointed the exec buffers (its values
                # win — Module's hooks flush beforehand on every public
                # path); stale pending results must not clobber them.
                # Pending optimizer/aux write-backs are dropped WITH the
                # externally-set weights' blessing — warn so bypassing the
                # public API is diagnosable (Module always flushes first).
                if not getattr(self, "_flushed", True):
                    _log.warning(
                        "fused step: exec buffers were repointed externally "
                        "with results pending; dropping the pending "
                        "optimizer-state/aux write-backs (use the public "
                        "Module APIs, which flush first)")
                self._flushed = True
            self._place_all()

        exec0 = self._exec0
        n_inputs_ok = all(
            len(list(b.data) + list(b.label or [])) == len(self._input_names)
            for b in batches)
        if not n_inputs_ok:
            self.flush()   # caller runs unfused on the public buffers
            return False
        if self._dp_size > 1 and any(
                (shape[0] if shape else 0) % self._dp_size
                for shape, _dt in in_sig):
            # e.g. a partial tail batch: not shardable over the dp axis —
            # this batch takes the unfused path, the step stays usable
            self.flush()
            return False
        try:
            xs_inputs = []
            for b in batches:
                data = list(b.data) + list(b.label or [])
                pre = getattr(self, "_prestaged", None)
                if pre is not None and pre[0] is b:
                    xs_inputs.append(pre[1])  # transfer already in flight
                    self._prestaged = None
                else:
                    xs_inputs.append(self._stage_inputs(data))
            fixed = [exec0.arg_dict[n]._data for n in self._fixed_names]
            if carry is not None:
                ws, ss, auxs = carry  # shardings unchanged (constrained)
            else:
                states = [self._updater.states[i] for i in self._indices]
                ws = [exec0.arg_dict[n]._data for n in self._param_names]
                ss = tuple(_state_data(s) for s in states)
                auxs = [exec0.aux_dict[n]._data for n in self._aux_names]
                self._call_w_shardings = [getattr(w, "sharding", None)
                                          for w in ws]
                self._call_s_shardings = tuple(_sharding_tree(s)
                                               for s in states)
                self._call_a_shardings = [getattr(a, "sharding", None)
                                          for a in auxs]
                # cold dispatch: these arrays may be externally staged
                # (checkpoint restore, set_params at epoch boundaries) —
                # donating host-staged buffers into an AOT executable
                # corrupts them; re-own through one XLA copy first
                ws, ss, auxs = reown_for_donation((ws, ss, auxs))

            mcarry = []
            for fn, m in metric_fns:
                pend = getattr(m, "_device_totals", None)
                if pend is None:
                    import jax.numpy as jnp
                    pend = (jax.device_put(jnp.zeros((), jnp.float32),
                                           self._rep_sharding),
                            jax.device_put(jnp.zeros((), jnp.int32),
                                           self._rep_sharding))
                mcarry.append(tuple(pend))

            if self._key is None:
                from . import random as _random
                self._key = jax.device_put(_random.next_key(),
                                           self._rep_sharding)
        except Exception as e:
            # placement/staging failure: this batch runs unfused; the
            # fused step itself stays usable for the next one
            _log.warning("fused step input staging failed (%s); running "
                         "this batch unfused", str(e)[:200])
            self.flush()
            return False

        # recompilation audit: past every unfused-bail check, a changed
        # signature now really does force a fresh XLA compile — record it
        # with the exact arg that moved (noting any earlier would claim
        # compiles for batches the eligibility checks sent unfused, and
        # poison the history for the eventual real compile)
        _analysis.recompile.note(self._audit_key, self._input_names, in_sig)
        if self._core_closed is not None and \
                in_sig != getattr(self, "_core_sig", None):
            # the input signature changed (the recompile auditor recorded
            # the churn above): the once-traced core jaxpr is
            # shape-specialized, so swap in this signature's cached
            # program set — or drop the core and re-trace.  A ragged tail
            # batch costs a recompile, not a permanently broken fast path.
            cached = getattr(self, "_core_cache", {}).get(in_sig)
            if cached is not None:
                (self._core_closed, self._jit, self._scan_jit,
                 self._jit_block, self._derive_ws, self._mp_pos,
                 self._w_dtypes, self._pod_axis,
                 self._pod_example, self._pod_plan,
                 self.pod_stats, self._autodonate_on) = cached
            else:
                self._core_closed = None

        opt = self._opt
        # snapshot counts so a failed attempt doesn't double-count the step
        # when the caller re-runs it through the unfused path
        counts_before = dict(opt._index_update_count)
        num_update_before = opt.num_update
        rows, rescale_dev = advance_hyper_rows(opt, self._indices, k, self,
                                               self._rep_sharding)
        t_vec = getattr(self, "_t_vec", None) if carry is not None else None
        if t_vec is None:
            # seed the in-graph counter with counts BEFORE this block (the
            # program itself adds +1 per step); re-owned — it is donated,
            # and device_put of host memory must not be (see
            # reown_for_donation)
            t_vec = reown_for_donation(jax.device_put(_np.asarray(
                [opt._index_update_count[i] - k for i in self._indices],
                _np.float32), self._rep_sharding))

        inner = (() if self._derive_ws and self._core_closed is not None
                 else tuple(ws), ss, tuple(auxs), tuple(mcarry),
                 self._key, t_vec)
        if self._guard:
            # the guardian's per-step gradient multipliers (1.0 outside
            # fault injection) ride the per-step inputs, and the site
            # hooks grad.nonfinite / loss.spike fire here — once per step
            gmuls = self._guardian.step_multipliers(k)
            xs = [(tuple(inp), lr_j, wd_j, gm)
                  for inp, (lr_j, wd_j), gm
                  in zip(xs_inputs, rows, gmuls)]
        else:
            xs = [(tuple(inp), lr_j, wd_j)
                  for inp, (lr_j, wd_j) in zip(xs_inputs, rows)]

        if _analysis.enabled():
            # name every donated carry leaf BEFORE the consuming dispatch:
            # a later read of a stale buffer then names its parameter and
            # the step that ate it (analysis.donation)
            self._step_no += k
            _analysis.donation.record(
                f"{self._audit_key} step {self._step_no}",
                self._donation_groups(ws, ss, auxs) +
                [("<metric accumulator>", mcarry),
                 ("<rng key>", self._key), ("<update counts>", t_vec)])

        try:
            with _no_rng():
                if self._core_closed is None:
                    core = self._build_core(metric_fns)
                    # derive mode decided inside _build_core: rebuild inner
                    if self._derive_ws:
                        inner = ((),) + inner[1:]
                    self._trace_core(core, (inner, xs[0], fixed,
                                            rescale_dev))
                    if self._pod_axis is not None and \
                            not self._pod_outs_ok():
                        # a reduced (non-batch-led) graph output cannot
                        # ride the pod fast path; re-trace global-view
                        _log.info("pod fast path disabled: graph outputs "
                                  "are not batch-led")
                        self._pod_axis = None
                        self.pod_stats = None
                        core = self._build_core(metric_fns)
                        self._trace_core(core, (inner, xs[0], fixed,
                                                rescale_dev))
                    if self._pod_axis is not None:
                        plan = getattr(self, "_pod_plan", [])
                        nbytes = sum(
                            int(_np.prod(w.shape)) * w.dtype.itemsize
                            for w in ws) if ws else 0
                        self.pod_stats = {
                            "axis": self._pod_axis, "dp": self._dp_size,
                            "params": len(self._param_names),
                            "buckets": len(plan),
                            # binds actually dispatched: the extras
                            # psum costs one extra when no f32 bucket
                            # existed to fold it into
                            "collectives_per_step": getattr(
                                self, "_pod_psums", len(plan)),
                            "bytes_per_step": nbytes,
                        }
                        from . import profiler as _profiler
                        _profiler.record_kvstore(
                            "pod_exchange", **self.pod_stats)
                    self._autodonate_on = self._decide_autodonate(
                        inner, xs[0])
                    self._jit = None
                    self._jit_block = {}
                    self._scan_jit = None
                if k == 1:
                    if self._jit is None:
                        self._build1()
                    if self._autodonate_on:
                        with _quiet_donation():
                            new_inner, outs = self._jit(
                                inner,
                                reown_for_donation(tuple(xs[0][0])),
                                tuple(xs[0][1:]), fixed, rescale_dev)
                    else:
                        new_inner, outs = self._jit(inner, xs[0], fixed,
                                                    rescale_dev)
                    ys = mys = None
                else:
                    jitk = self._jit_block.get(k)
                    if jitk is None:
                        jitk = self._buildk(k)
                    if self._autodonate_on:
                        with _quiet_donation():
                            new_inner, ys, mys, outs = jitk(
                                inner,
                                reown_for_donation(
                                    tuple(tuple(x[0]) for x in xs)),
                                tuple(tuple(x[1:]) for x in xs),
                                fixed, rescale_dev)
                    else:
                        new_inner, ys, mys, outs = jitk(
                            inner, tuple(xs), fixed, rescale_dev)
        except Exception as e:
            opt._index_update_count = counts_before
            opt.num_update = num_update_before
            if self._guard:
                # the block never dispatched: the guardian's step counter
                # must not count it (the unfused fallback is unguarded)
                self._guardian._gstep -= k
            try:
                _raise_if_unrecoverable("fused train step", e,
                                        self._donation_groups(ws, ss, auxs))
            except RuntimeError:
                self.broken = True
                self._carry = None
                self._t_vec = None
                self._block_view = None
                raise
            self.flush()   # pending results from prior steps are intact
            self._carry = None
            self._t_vec = None
            self._block_view = None
            self.broken = True
            _log.warning("fused train step unavailable (%s); Module.fit "
                         "falls back to forward_backward+update",
                         str(e)[:300])
            return False

        health = None
        if self._guard:
            # step_out is (outputs, (ok, signal)): split the health word
            # off the output views (device arrays — the guardian gathers
            # them asynchronously, never on this path)
            if ys is not None:
                ys, health = ys
                outs = outs[0]
            else:
                outs, health = outs
        new_ws, new_ss, new_aux, new_mcarry, new_key, new_t = new_inner
        finals = []
        for (fn, m), pend in zip(metric_fns, new_mcarry):
            t = tuple(pend)
            m._device_totals = t
            finals.append(t)
        # per-step metric exposure for the callback burst: batch-j
        # callbacks must see batch-j metric state, not block-final state
        if mys is not None:
            self._block_view = _BlockMetricView(
                [m for _, m in metric_fns], mys, finals)
            self._block_view.arm()
        else:
            self._block_view = None
        self._key = new_key
        self._t_vec = new_t
        ctx0 = self._contexts[0]
        self.last_outputs = [NDArray(o, ctx=ctx0) for o in outs]
        # per-batch outputs of the block (stacked scan ys): a batch-j
        # callback reading get_outputs() must see batch j's outputs, not
        # the block-final ones — the fit loop moves `block_cursor` as it
        # fires the callback burst and `current_outputs` slices lazily
        self._block_outs = ys
        self._block_len = k
        self.block_cursor = k - 1
        self._block_cache = {}
        mod._params_dirty = True
        # arm the steady-state fast path; the ~600 NDArray write-backs are
        # DEFERRED (donation invalidated the old buffers, but nothing reads
        # them until an external consumer calls flush() via Module) — on a
        # one-core host the per-step Python was serializing with the device
        was_cold = carry is None
        self._carry = (list(new_ws), tuple(new_ss), list(new_aux))
        self._carry_sdict = self._updater.states
        self._carry_in_sig = in_sig
        self._flushed = False
        self._core_sig = in_sig
        if len(self._core_cache) < 8 or in_sig in self._core_cache:
            # keep the freshest program set per signature so an
            # alternating shape (epoch tail) swaps instead of re-tracing
            self._core_cache[in_sig] = (
                self._core_closed, self._jit, self._scan_jit,
                self._jit_block, self._derive_ws,
                getattr(self, "_mp_pos", None),
                getattr(self, "_w_dtypes", None),
                self._pod_axis, getattr(self, "_pod_example", None),
                getattr(self, "_pod_plan", None), self.pod_stats,
                self._autodonate_on)
        if was_cold:
            # first step of a signature: write through immediately so the
            # `_seen_*` identity snapshots exist for the fast-path check
            self.flush()
        if health is not None:
            self._guardian.record_health(k, health[0], health[1])
        return True

    def _donation_groups(self, ws, ss, auxs):
        """(owner_name, pytree) pairs for every donated persistent buffer
        — the donation tracker's and the unrecoverable-failure error's
        naming source."""
        groups = list(zip(self._param_names, ws))
        groups += [(n + ".state", s) for n, s in zip(self._param_names, ss)]
        groups += list(zip(self._aux_names, auxs))
        return groups

    def _stage_inputs(self, data):
        """Place a batch's arrays onto the data sharding (dtype-cast
        host-side first — e.g. fp32 pipeline output to a bf16 model —
        which also halves the host->device bytes)."""
        import jax
        exec0 = self._exec0
        inputs = []
        for v, name in zip(data, self._input_names):
            raw = v._data if isinstance(v, NDArray) else _np.asarray(v)
            tgt = exec0.arg_dict[name]
            if hasattr(raw, "astype") and raw.dtype != tgt.dtype and \
                    name not in self._mod._exec_group.label_names:
                raw = raw.astype(tgt.dtype)
            if getattr(raw, "sharding", None) == self._data_sharding:
                inputs.append(raw)  # already placed; skip the dispatch
            else:
                inputs.append(jax.device_put(raw, self._data_sharding))
        return inputs

    def prestage(self, data_batch):
        """Start the (async) device placement of a FUTURE batch while the
        current step's program is still executing — the reference
        PrefetcherIter's H2D pipelining role (`src/io/iter_prefetcher.h`),
        driven from `Module.prepare` in the fit loop.  `_dispatch` adopts
        the in-flight transfer by batch identity."""
        if self.broken:
            return
        try:
            data = list(data_batch.data) + list(data_batch.label or [])
            if len(data) != len(self._input_names):
                return
            self._prestaged = (data_batch, self._stage_inputs(data))
        except Exception:
            self._prestaged = None

    def ring_placement(self):
        """This step's staging target for the h2d ring
        (`io_plane.RingPlacement`): the data sharding plus per-input
        target dtypes, exactly what `_stage_inputs` produces — so ring
        batches are adopted by sharding identity with no second
        transfer and no signature churn (zero steady-state
        recompiles)."""
        from .io_plane import RingPlacement
        return RingPlacement.for_fused_step(self)

    def set_block_cursor(self, j):
        """Point `get_outputs()` AND the in-graph metrics at logical
        step j of the last block — the fit loop calls this as it fires
        the batch-j callback burst, so each batch-end callback observes
        per-step state (outputs + metric totals), not block-final
        state."""
        self.block_cursor = j
        if self._block_view is not None:
            self._block_view.expose(j)

    def cached_programs(self):
        """The live CachedPrograms this step compiled (current signature
        plus every cached alternate) — the checkpoint ``programs/``
        payload's source."""
        progs = {}
        for p in (self._jit, getattr(self, "_scan_jit", None)):
            if p is not None and hasattr(p, "export_to"):
                progs[id(p)] = p
        for entry in getattr(self, "_core_cache", {}).values():
            for p in entry[1:3]:
                if p is not None and hasattr(p, "export_to"):
                    progs[id(p)] = p
        return list(progs.values())

    def export_programs(self, directory):
        """Serialize this step's compiled executables into `directory`
        as program-cache entries (checkpoint payload); returns count."""
        return sum(p.export_to(directory) for p in self.cached_programs())

    def compile_phase_stats(self):
        """Cold-start phase breakdown for the traced step: framework
        trace seconds, the traced jaxpr's (recursive) equation count —
        the graph-size number the XLA compile scales with, ONE layer
        body per scan-deduped run — and per-program lower/compile
        seconds from the unified cache (bench's `compile_phases`
        artifact block reads this)."""
        core = getattr(self, "_core_closed", None)
        out = {
            "trace_s": getattr(core, "trace_s", None)
            if core is not None else None,
            "jaxpr_eqns": core.num_eqns() if core is not None else None,
            "scan_runs": list(getattr(self, "scan_runs", []) or []),
            "autodonate": bool(getattr(self, "_autodonate_on", False)),
            "programs": [],
        }
        for p in self.cached_programs():
            out["programs"].append({
                "label": getattr(p, "label", ""),
                "compiles": int(getattr(p, "compile_count", 0)),
                "disk_hits": int(getattr(p, "disk_hits", 0)),
                "lower_s": float(getattr(p, "lower_s_total", 0.0)),
                "compile_s": float(getattr(p, "compile_s_total", 0.0)),
            })
        return out

    def current_outputs(self):
        """Outputs of the batch `block_cursor` points at (per-batch view
        into the scan ys), or the plain last outputs, or None when the
        last step did not run fused."""
        ys = getattr(self, "_block_outs", None)
        if ys is not None:
            j = min(getattr(self, "block_cursor", self._block_len - 1),
                    self._block_len - 1)
            if j == self._block_len - 1:
                return self.last_outputs
            got = self._block_cache.get(j)
            if got is None:
                ctx0 = self._contexts[0]
                got = [NDArray(y[j], ctx=ctx0) for y in ys]
                self._block_cache[j] = got
            return got
        return self.last_outputs

    def clear_outputs(self):
        """Invalidate output views (an unfused forward/step supersedes)."""
        self.last_outputs = None
        self._block_outs = None

    def _owns_exec_buffers(self):
        """True while the exec dicts still hold the arrays WE last wrote
        (nobody repointed them externally since the last flush)."""
        seen = getattr(self, "_seen_ws", None)
        if seen is None:
            return True
        exec0 = self._exec0
        return all(exec0.arg_dict[n]._data is w
                   for n, w in zip(self._param_names, seen))

    def _derived_weights(self, new_ss):
        """Low-precision weights re-derived from the fp32 masters — only
        flush pays this (a tiny cast program), never the hot loop."""
        import jax
        if self._derive_fn is None:
            mp_pos, dts = self._mp_pos, self._w_dtypes

            def derive(ss):
                return tuple(
                    jax.tree_util.tree_leaves(s)[p].astype(dt)
                    for s, p, dt in zip(ss, mp_pos, dts))

            self._derive_fn = jax.jit(derive)
        return list(self._derive_fn(tuple(new_ss)))

    def flush(self):
        """Write the pending step results (deferred donated-carry arrays)
        into the public NDArrays: parameters, optimizer state, aux states.
        Steady-state training never needs this; any external reader —
        get_params, checkpointing, the unfused fallback, a forward() —
        must see current values, so Module routes through here first."""
        if getattr(self, "_flushed", True) or self._carry is None:
            return
        self._flushed = True
        new_ws, new_ss, new_aux = self._carry
        if self._derive_ws and not new_ws:
            new_ws = self._derived_weights(new_ss)
        groups = self._mod._exec_group
        for n, nw in zip(self._param_names, new_ws):
            for e in groups.execs:
                e.arg_dict[n]._set_data(nw)
        states = [self._updater.states[i] for i in self._indices]
        for s, ns in zip(states, new_ss):
            _state_write_back(s, ns)
        for n, na in zip(self._aux_names, new_aux):
            for e in groups.execs:
                e.aux_dict[n]._set_data(na)
        self._seen_ws = list(new_ws)
        self._seen_aux = list(new_aux)

    def _metric_sig(self):
        return getattr(self, "_metric_ids", None)


def metric_fns_changed(prev_ids, metric_fns):
    return prev_ids != [id(m) for _, m in metric_fns]


# ---------------------------------------------------------------------------
# FusedInference: the request path's per-signature program cache
# ---------------------------------------------------------------------------

class FusedInference:
    """Inference over a pinned parameter set as one XLA program per input
    signature — the request-path face of the per-signature caches the
    fused train steps keep.

    The whole Symbol compiles to ONE program (graph_eval_fn); parameters
    and aux states are device-resident constants of the call, so every
    dispatch ships only the request tensors.  `jax.jit`'s own cache keys
    on the input signature: a fixed set of shape buckets therefore costs
    exactly one compile each (paid at warmup), and every dispatch is
    noted with the recompile auditor under `audit_key` so
    ``MXNET_ANALYSIS=1`` can certify zero post-warmup compiles.

    Thread-safe for concurrent callers: dispatch state is per-call; the
    only mutation, `set_params`, swaps the whole param list atomically
    (in-flight calls finish against the snapshot they captured).
    """

    def __init__(self, symbol, ctx, data_names, audit_key=None):
        import jax
        from .symbol.symbol import graph_eval_fn
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        unknown = [n for n in data_names if n not in self._arg_names]
        if unknown:
            # silently filtering would misalign every later input list
            raise MXNetError(
                f"FusedInference: data names {unknown} are not arguments "
                f"of the symbol (has {self._arg_names})")
        self._data_names = list(data_names)
        # every non-data argument is a candidate parameter slot; slots the
        # param dict never fills (e.g. a loss head's label input, whose
        # shape follows the batch) become per-call inputs instead —
        # `extra_names` after set_params — fed zeros by the serving layer
        self._slot_names = [n for n in self._arg_names
                            if n not in self._data_names]
        self._input_names = list(self._data_names)
        self._scan_plan = _maybe_scan_plan(symbol)
        self.scan_runs = [] if self._scan_plan is None else \
            [(r["name"], r["length"]) for r in self._scan_plan["runs"]]
        self._gfn, _, _, self._n_rng = graph_eval_fn(
            symbol, False, scan=self._scan_plan)
        # (jit, extra_names, params, aux): ONE reference, swapped whole,
        # so a concurrent dispatch never pairs a rebuilt program with the
        # previous partition's param list (or new params with old aux)
        self._state = None
        self._graph_hash = None   # lazy symbol-JSON hash (disk-tier key)
        self._key = jax.random.PRNGKey(0)   # inference path draws nothing
        FusedInference._seq = getattr(FusedInference, "_seq", 0) + 1
        self.audit_key = audit_key or f"FusedInference#{FusedInference._seq}"

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def set_params(self, arg_params, aux_params=None, aux_shapes=None):
        """Pin the parameter set: every argument `arg_params` covers and
        every aux state becomes a device-resident array, moved in ONE
        batched transfer.  Uncovered argument slots become per-call
        inputs (`extra_names` — their shapes may follow the batch); aux
        states absent from `aux_params` are zeros of ``aux_shapes[name]``
        (the `Executor._simple_bind` convention).  Atomic with respect to
        concurrent dispatches: in-flight calls finish against the
        (params, aux) snapshot they captured."""
        import jax
        aux_params = aux_params or {}
        aux_shapes = aux_shapes or {}
        param_names = [n for n in self._slot_names if n in arg_params]
        extra_names = [n for n in self._slot_names if n not in arg_params]

        def value(v):
            return v._data if isinstance(v, NDArray) else _np.asarray(v)

        plan = [value(arg_params[n]) for n in param_names]
        for n in self._aux_names:
            if n in aux_params:
                plan.append(value(aux_params[n]))
            elif n in aux_shapes:
                plan.append(_np.zeros(aux_shapes[n], _np.float32))
            else:
                raise MXNetError(
                    f"FusedInference: no value or shape for aux '{n}'")
        moved = jax.device_put(plan, self._ctx.jax_device)
        state = self._state
        if state is not None and state[1] == extra_names:
            jit = state[0]   # same partition: keep every compiled program
        else:
            jit = self._build(param_names, extra_names)
        self._state = (jit, extra_names,
                       moved[:len(param_names)], moved[len(param_names):])

    @property
    def extra_names(self):
        """Argument slots fed per-call (shapes may follow the batch)."""
        return self._state[1] if self._state is not None else []

    def _build(self, param_names, extra_names):
        from .compile import cached_jit, graph_hash_of_text
        gfn = self._gfn
        param_pos = {n: k for k, n in enumerate(param_names)}
        input_pos = {n: k for k, n in enumerate(self._input_names)}
        extra_pos = {n: k for k, n in enumerate(extra_names)}
        arg_names = self._arg_names

        def run(params, inputs, extras, aux, key):
            args = []
            for n in arg_names:
                if n in param_pos:
                    args.append(params[param_pos[n]])
                elif n in input_pos:
                    args.append(inputs[input_pos[n]])
                else:
                    args.append(extras[extra_pos[n]])
            outs, _ = gfn(tuple(args), tuple(aux), key)
            return outs

        # symbol JSON (not object identity) keys the disk tier: a fresh
        # process loading the same graph hits the serialized executables
        if self._graph_hash is None:
            self._graph_hash = graph_hash_of_text(self._symbol.tojson())
        return cached_jit(
            run,
            graph_key=("infer", self._graph_hash, tuple(param_names),
                       tuple(extra_names), tuple(self._input_names)),
            label=self.audit_key)

    def signature(self, inputs):
        """(shape, dtype) per data input — the recompile auditor's
        currency for this program."""
        return tuple((tuple(v.shape), str(v.dtype)) for v in inputs)

    def program_count(self):
        """Compiled programs so far (one per signature)."""
        return self._state[0]._cache_size() if self._state is not None \
            else 0

    def cached_programs(self):
        """The live CachedProgram behind the current partition."""
        state = self._state
        if state is not None and hasattr(state[0], "export_to"):
            return [state[0]]
        return []

    def export_programs(self, directory):
        """Serialize the compiled bucket programs into `directory` as
        program-cache entries (warmed-image / payload export)."""
        return sum(p.export_to(directory) for p in self.cached_programs())

    def register_warm(self, inputs):
        """Declare `inputs`' signature as an expected bucket BEFORE
        compiling it, so warmup compiles never read as shape churn."""
        from .analysis import recompile as _recompile
        _recompile.register(self.audit_key, self._input_names,
                            self.signature(inputs))

    def __call__(self, inputs, extras=()):
        """Run the program for `inputs` (raw arrays ordered like
        `data_names`; `extras` ordered like `extra_names`); returns the
        raw output arrays."""
        state = self._state
        if state is None:
            raise MXNetError("FusedInference: set_params before calling")
        jit, extra_names, params, aux = state
        if len(extras) != len(extra_names):
            # caller built extras against a partition a concurrent
            # set_params just replaced: fail clean (retryable), never
            # bind the wrong arrays
            raise MXNetError(
                "FusedInference: extras changed under a concurrent "
                "set_params; retry the request")
        from .analysis import recompile as _recompile
        _recompile.note(self.audit_key, self._input_names,
                        self.signature(inputs))
        return jit(params, list(inputs), list(extras), aux, self._key)
