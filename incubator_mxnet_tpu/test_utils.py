"""Test utilities (reference `python/mxnet/test_utils.py`).

Carries the reference's operator-test backbone: `check_numeric_gradient`
(finite differences vs registered gradients, reference :790),
`check_symbolic_forward`/`backward` (:923), `assert_almost_equal` (:470),
and `check_consistency` (:1204) — the cross-backend parity harness the TPU
build uses to compare tpu vs cpu executions of the same symbol.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array
from . import ndarray as nd

_default_ctx = [None]


def default_context():
    """Reference `test_utils.py:53 default_context`."""
    return _default_ctx[0] or current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, **kwargs):
    ctx = ctx or default_context()
    arr = np.random.uniform(-1, 1, shape).astype(dtype or "float32")
    if stype == "default":
        return array(arr, ctx=ctx, dtype=dtype)
    from .ndarray import sparse
    if density is not None:
        mask = np.random.rand(*shape) < density
        arr = arr * mask
    return sparse.cast_storage(array(arr, ctx=ctx), stype)


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference `test_utils.py:470 assert_almost_equal`."""
    if isinstance(a, NDArray):
        a = a.asnumpy()
    if isinstance(b, NDArray):
        b = b.asnumpy()
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan,
                               err_msg=f"{names[0]} vs {names[1]}")


def _parse_location(sym, location, ctx, dtype=np.float32):
    if isinstance(location, dict):
        sorted_locations = [location[name] for name in sym.list_arguments()
                            if name in location]
        location = {k: array(v, ctx=ctx, dtype=getattr(v, "dtype", dtype))
                    if not isinstance(v, NDArray) else v
                    for k, v in location.items()}
        return location
    location = {k: array(v, ctx=ctx, dtype=getattr(v, "dtype", dtype))
                if not isinstance(v, NDArray) else v
                for k, v in zip(sym.list_arguments(), location)}
    return location


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients of sum(outputs) w.r.t. each argument."""
    approx_grads = {}
    for name, arr in location.items():
        base = arr.asnumpy().astype("float64")
        grad = np.zeros_like(base)
        it = np.nditer(base, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            orig = base[idx]
            for sign in (1, -1):
                base[idx] = orig + sign * eps
                executor.arg_dict[name]._data = \
                    executor.arg_dict[name]._data * 0 + base.astype(
                        np.asarray(executor.arg_dict[name].asnumpy()).dtype)
                outs = executor.forward(is_train=use_forward_train)
                val = sum(float(o.asnumpy().astype("float64").sum())
                          for o in outs)
                if sign == 1:
                    fplus = val
                else:
                    fminus = val
            base[idx] = orig
            grad[idx] = (fplus - fminus) / (2 * eps)
            it.iternext()
        executor.arg_dict[name]._data = executor.arg_dict[name]._data * 0 + \
            base.astype(np.asarray(executor.arg_dict[name].asnumpy()).dtype)
        approx_grads[name] = grad
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, grad_stype_dict=None,
                           dtype=np.float64):
    """Reference `test_utils.py:790 check_numeric_gradient`: compare the
    registered (vjp) gradient against central finite differences."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    if grad_nodes is None:
        grad_nodes = [name for name in sym.list_arguments()
                      if name in location]
    shapes = {k: v.shape for k, v in location.items()}
    ex = sym.simple_bind(ctx=ctx, grad_req={
        name: ("write" if name in grad_nodes else "null")
        for name in sym.list_arguments()}, **shapes)
    for k, v in location.items():
        ex.arg_dict[k]._data = ex.arg_dict[k]._data * 0 + v._data.astype(
            ex.arg_dict[k].dtype)
    if aux_states:
        for k, v in aux_states.items():
            ex.aux_dict[k]._data = ex.aux_dict[k]._data * 0 + (
                v._data if isinstance(v, NDArray) else np.asarray(v))
    ex.forward(is_train=use_forward_train)
    ex.backward()
    analytic = {name: ex.grad_dict[name].asnumpy() for name in grad_nodes}
    approx = numeric_grad(ex, {k: location[k] for k in grad_nodes},
                          eps=numeric_eps,
                          use_forward_train=use_forward_train)
    for name in grad_nodes:
        assert_almost_equal(analytic[name], approx[name], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=(f"analytic_{name}", f"numeric_{name}"))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, dtype=np.float32,
                           equal_nan=False):
    """Reference `test_utils.py:923 check_symbolic_forward`."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    shapes = {k: v.shape for k, v in location.items()}
    ex = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    for k, v in location.items():
        ex.arg_dict[k]._data = ex.arg_dict[k]._data * 0 + v._data.astype(
            ex.arg_dict[k].dtype)
    if aux_states:
        for k, v in aux_states.items():
            src = v._data if isinstance(v, NDArray) else np.asarray(v)
            ex.aux_dict[k]._data = ex.aux_dict[k]._data * 0 + src
    outputs = ex.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-20,
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, grad_stypes=None, equal_nan=False,
                            dtype=np.float32):
    """Reference `test_utils.py check_symbolic_backward`."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    shapes = {k: v.shape for k, v in location.items()}
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    ex = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
    for k, v in location.items():
        ex.arg_dict[k]._data = ex.arg_dict[k]._data * 0 + v._data.astype(
            ex.arg_dict[k].dtype)
    ex.forward(is_train=True)
    if out_grads is not None and not isinstance(out_grads, (list, tuple)):
        out_grads = [out_grads]
    if out_grads is not None:
        out_grads = [array(g, ctx=ctx) if not isinstance(g, NDArray) else g
                     for g in out_grads]
    ex.backward(out_grads)
    grads = {name: ex.grad_dict[name].asnumpy() for name in expected
             if ex.grad_dict.get(name) is not None}
    for name, exp in expected.items():
        if name in grads:
            assert_almost_equal(grads[name], exp, rtol=rtol,
                                atol=atol if atol is not None else 1e-20,
                                names=(f"grad_{name}", "expected"))
    return grads


def check_consistency(sym, ctx_list, scale=1.0, dtype=None,
                      grad_req="write", arg_params=None, aux_params=None,
                      tol=None, raise_on_err=True, ground_truth=None,
                      equal_nan=False, use_uniform=False):
    """Reference `test_utils.py:1204 check_consistency`: run one symbol on
    several (ctx, dtype) configurations, compare outputs and gradients.  This
    is THE TPU-vs-CPU parity harness."""
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
               np.dtype(np.int32): 0, np.dtype(np.int64): 0}
    elif isinstance(tol, float):
        tol = {np.dtype(t): tol for t in (np.float16, np.float32, np.float64,
                                          np.uint8, np.int32, np.int64)}

    assert len(ctx_list) > 1
    if isinstance(sym, (list, tuple)):
        sym_list = list(sym)
    else:
        sym_list = [sym] * len(ctx_list)

    output_data = []
    grad_datas = []
    arg_names = sym_list[0].list_arguments()

    # generate shared random inputs from the first config's shapes
    shapes = {k: v for k, v in ctx_list[0].items() if k != "ctx" and
              not k.endswith("type_dict")}
    np.random.seed(0)
    base_inputs = {}

    for config, s in zip(ctx_list, sym_list):
        ctx = config["ctx"]
        cshapes = {k: v for k, v in config.items() if k != "ctx" and
                   not k.endswith("type_dict")}
        type_dict = config.get("type_dict", {})
        ex = s.simple_bind(ctx=ctx, grad_req=grad_req, type_dict=type_dict,
                           **cshapes)
        for name in arg_names:
            if name not in base_inputs:
                base_inputs[name] = np.random.normal(
                    size=ex.arg_dict[name].shape, scale=scale)
            src = base_inputs[name]
            ex.arg_dict[name]._data = ex.arg_dict[name]._data * 0 + \
                src.astype(ex.arg_dict[name].dtype)
        if arg_params:
            for k, v in arg_params.items():
                ex.arg_dict[k]._data = ex.arg_dict[k]._data * 0 + \
                    np.asarray(v).astype(ex.arg_dict[k].dtype)
        if aux_params:
            for k, v in aux_params.items():
                ex.aux_dict[k]._data = ex.aux_dict[k]._data * 0 + \
                    np.asarray(v).astype(ex.aux_dict[k].dtype)
        outs = ex.forward(is_train=grad_req != "null")
        if grad_req != "null":
            ex.backward()
            grad_datas.append({name: ex.grad_dict[name].asnumpy()
                               for name in arg_names
                               if ex.grad_dict.get(name) is not None})
        output_data.append([o.asnumpy() for o in outs])

    # compare everything against the most precise config (last one by
    # convention in the reference: fp64 cpu last)
    gt_idx = len(output_data) - 1
    max_dtype = max((np.dtype(o.dtype) for o in output_data[gt_idx]),
                    key=lambda d: d.itemsize)
    for i, outs in enumerate(output_data):
        if i == gt_idx:
            continue
        this_tol = max(tol.get(np.dtype(outs[0].dtype), 1e-3),
                       tol.get(max_dtype, 1e-5))
        for o, gt in zip(outs, output_data[gt_idx]):
            assert_almost_equal(o.astype("float64"), gt.astype("float64"),
                                rtol=this_tol, atol=this_tol,
                                equal_nan=equal_nan)
    if grad_req != "null":
        for i, grads in enumerate(grad_datas):
            if i == gt_idx:
                continue
            for name in grads:
                this_tol = max(tol.get(np.dtype(grads[name].dtype), 1e-3),
                               tol.get(max_dtype, 1e-5))
                assert_almost_equal(grads[name].astype("float64"),
                                    grad_datas[gt_idx][name].astype("float64"),
                                    rtol=this_tol, atol=this_tol,
                                    names=(f"grad_{name}_{i}", "ground_truth"),
                                    equal_nan=equal_nan)
    return output_data


def get_mnist_like(num=1000, seed=0):
    """Synthetic MNIST-like dataset (deterministic) for e2e train tests —
    replaces the reference's downloaded MNIST in this zero-egress env."""
    rng = np.random.RandomState(seed)
    # 10 class prototypes + noise: linearly separable enough for LeNet/MLP
    protos = rng.rand(10, 1, 28, 28).astype("f4")
    labels = rng.randint(0, 10, num)
    imgs = protos[labels] + 0.1 * rng.rand(num, 1, 28, 28).astype("f4")
    return imgs.astype("f4"), labels.astype("f4")


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


# -- environment capability probes (skip-guards for tier-1 tests) -------------
# The parallel/ and dist/ subsystems target jax builds with (a) the
# stable `jax.shard_map` export and (b) multiprocess collectives on the
# CPU backend (the pod test mesh).  Containers with an older jaxlib lack
# one or both; tests gate on these probes instead of failing red, so a
# tier-1 run is green everywhere and the skips NAME the missing
# capability.

def has_stable_shard_map():
    """Whether this jax exports the stable ``jax.shard_map`` API the
    parallel subsystem (data_parallel, zero, pipeline, ring_attention,
    gluon TP/ZeRO sharding — all written and tolerance-calibrated
    against it) requires."""
    try:
        from jax import shard_map  # noqa: F401
        return True
    except ImportError:
        return False


_MP_COLLECTIVES_PROBE = """
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
rank, port = int(sys.argv[1]), int(sys.argv[2])
jax.distributed.initialize(coordinator_address="127.0.0.1:%d" % port,
                           num_processes=2, process_id=rank)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
reps = [[d for d in jax.devices() if d.process_index == p][0]
        for p in range(2)]
mesh = Mesh(np.array(reps), ("w",))
local = jax.device_put(np.full(4, rank + 1.0), reps[rank])[None]
garr = jax.make_array_from_single_device_arrays(
    (2, 4), NamedSharding(mesh, P("w")), [local])
out = jax.jit(lambda x: x.sum(axis=0),
              out_shardings=NamedSharding(mesh, P()))(garr)
assert float(np.asarray([s.data for s in out.addressable_shards][0])[0]) \\
    == 3.0
"""

_mp_collectives_cache = [None]


def has_multiprocess_cpu_collectives(timeout=90):
    """Whether TWO processes can jointly execute an XLA reduction over a
    global CPU mesh (the dist kvstore collective plane's recipe).  Older
    jaxlib raises 'Multiprocess computations aren't implemented on the
    CPU backend' at dispatch; this probes the real execution path in two
    throwaway subprocesses and caches the verdict for the session."""
    if _mp_collectives_cache[0] is None:
        import socket
        import subprocess
        import sys
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _MP_COLLECTIVES_PROBE, str(r), str(port)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for r in range(2)]
        ok = True
        for p in procs:
            try:
                ok &= p.wait(timeout=timeout) == 0
            except subprocess.TimeoutExpired:
                p.kill()
                ok = False
        _mp_collectives_cache[0] = ok
    return _mp_collectives_cache[0]
