"""Control-flow operators: `_foreach`, `_while_loop`, `_cond`.

Reference: `src/operator/control_flow.cc:1255-1423` (+ subgraph plumbing in
`subgraph_op_common.cc`), where each op carries CachedOp subgraphs executed
by an interpreter loop on the engine.  Here the lowering is direct and
TPU-native: the subgraph (stored as symbol JSON in the op attrs, so graphs
save/load like any other) is evaluated through `graph_eval_fn` inside

* `_foreach`     -> `jax.lax.scan`   (slices scan on axis 0, states carry)
* `_while_loop`  -> a masked `lax.scan` over max_iterations (static shapes
                    are what the XLA compilation model wants; entries past
                    termination are zeros, the reference leaves them
                    undefined — `docs` of nd.contrib.while_loop).  With NO
                    per-step outputs (num_out_data == 0) and outside
                    training, a TRUE `lax.while_loop` runs instead: early
                    termination, cost scales with actual iterations
* `_cond`        -> `jax.lax.cond`

so a hybridized RNN becomes ONE scan in the compiled program instead of T
unrolled cell bodies, and gradients come from jax's scan/cond vjp instead
of the reference's per-op backward interpreter.

Input layout (built by `symbol/contrib.py`): tensor inputs are
[data..., states..., closure...] for `_foreach`, [vars..., closure...] for
`_while_loop`, [pred, closure...] for `_cond`; `arg_map` in the attrs maps
each subgraph argument NAME to its slot ("d0"/"s1"/"v0"/"c2"), so the
rebuilt-from-JSON subgraph binds by name, not by object identity.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED
from ..base import MXNetError


def _json_str(v):
    """Keep subgraph attrs as canonical JSON strings: `py_literal` may have
    parsed a pure-literal JSON document into a dict on symbol reload."""
    if isinstance(v, str):
        return v
    import json
    return json.dumps(_delist(v))


def _delist(v):
    if isinstance(v, tuple):
        return [_delist(x) for x in v]
    if isinstance(v, dict):
        return {k: _delist(x) for k, x in v.items()}
    return v


@functools.lru_cache(maxsize=256)
def _subgraph(json_str):
    from ..symbol.symbol import load_json
    sym = load_json(json_str)
    if sym.list_auxiliary_states():
        raise MXNetError(
            "control-flow subgraphs with auxiliary states (BatchNorm "
            "running stats) are not supported; move the stateful layer "
            "outside the loop body")
    return sym


def _sub_eval(json_str, train):
    """(eval_fn, arg_names) for a stored subgraph."""
    from ..symbol.symbol import graph_eval_fn
    sym = _subgraph(json_str)
    gfn, _, _, _ = graph_eval_fn(sym, train)
    return gfn, sym.list_arguments()


def _binder(arg_names, arg_map):
    """Positions of each subgraph argument: (kind, index) per name.

    `arg_map` entries are emitted in the subgraph's topo order over
    variable nodes (`symbol/contrib.py _classify_args`) — the SAME order
    `list_arguments()` yields after the JSON round trip — so binding is
    POSITIONAL.  Binding through a name->tag dict would collapse two
    distinct outer Variables that share a name (legal in the symbol API,
    and common in nested foreach/while_loop bodies reusing inner names)
    onto one slot, silently computing with the wrong input."""
    entries = [(n, t) for n, t in arg_map]
    if len(entries) == len(arg_names) and \
            all(n == en for n, (en, _t) in zip(arg_names, entries)):
        return [(t[0], int(t[1:])) for _n, t in entries]
    # name order disagrees (a hand-edited graph JSON): fall back to
    # name-keyed binding, refusing ambiguity instead of mis-binding
    amap = {}
    for n, t in entries:
        if n in amap and amap[n] != t:
            raise MXNetError(
                f"control-flow subgraph has two inputs named {n!r} with "
                "different slots and a reordered arg_map; cannot bind "
                "unambiguously — give loop-body inputs unique names")
        amap[n] = t
    slots = []
    for n in arg_names:
        tag = amap.get(n)
        if tag is None:
            raise MXNetError(f"control-flow subgraph argument {n!r} has no "
                             "slot mapping (corrupt arg_map)")
        slots.append((tag[0], int(tag[1:])))
    return slots


_FOREACH_PARAMS = {
    "num_args": REQUIRED, "subgraph": REQUIRED, "arg_map": REQUIRED,
    "num_data": REQUIRED, "num_states": REQUIRED, "num_out_data": REQUIRED,
}


@register("_foreach", nin=-1, variadic_param="num_args",
          params=_FOREACH_PARAMS,
          param_types={"subgraph": _json_str},
          nout=lambda p: int(p["num_out_data"]) + int(p["num_states"]),
          needs_rng=True, mode_dependent=True)
def _foreach(params, *arrays):
    """reference control_flow.cc:1255 (ForeachState + ForeachComputeExCPU)
    lowered to one `lax.scan`."""
    train = bool(params.get("_train", False))
    gfn, arg_names = _sub_eval(params["subgraph"], train)
    slots = _binder(arg_names, params["arg_map"])
    nd_ = int(params["num_data"])
    ns = int(params["num_states"])
    n_out = int(params["num_out_data"])
    key = arrays[-1]
    arrays = arrays[:-1]
    data = tuple(arrays[:nd_])
    states = tuple(arrays[nd_:nd_ + ns])
    closure = tuple(arrays[nd_ + ns:])

    def pick(xs, st):
        return tuple(xs[i] if k == "d" else st[i] if k == "s" else closure[i]
                     for k, i in slots)

    def body(carry, xs):
        st, k = carry
        k, sk = jax.random.split(k)
        outs, _ = gfn(pick(xs, st), (), sk)
        return (tuple(outs[n_out:]), k), tuple(outs[:n_out])

    (fin_states, _), ys = jax.lax.scan(body, (states, key), data)
    return tuple(ys) + tuple(fin_states)


_WHILE_PARAMS = {
    "num_args": REQUIRED, "cond_subgraph": REQUIRED, "func_subgraph": REQUIRED,
    "cond_arg_map": REQUIRED, "func_arg_map": REQUIRED,
    "num_vars": REQUIRED, "num_out_data": REQUIRED,
    "max_iterations": REQUIRED,
}


@register("_while_loop", nin=-1, variadic_param="num_args",
          params=_WHILE_PARAMS,
          param_types={"cond_subgraph": _json_str,
                       "func_subgraph": _json_str},
          nout=lambda p: int(p["num_out_data"]) + int(p["num_vars"]),
          needs_rng=True, mode_dependent=True)
def _while_loop(params, *arrays):
    """reference control_flow.cc `_while_loop` as a masked scan: static
    max_iterations trip count (what the symbolic reference op also
    requires), with an `active` predicate freezing vars once the condition
    fails.  Outputs are padded to max_iterations; padding rows are zeros
    (reference: undefined)."""
    train = bool(params.get("_train", False))
    cfn, c_names = _sub_eval(params["cond_subgraph"], train)
    ffn, f_names = _sub_eval(params["func_subgraph"], train)
    c_slots = _binder(c_names, params["cond_arg_map"])
    f_slots = _binder(f_names, params["func_arg_map"])
    nv = int(params["num_vars"])
    n_out = int(params["num_out_data"])
    max_iter = int(params["max_iterations"])
    key = arrays[-1]
    arrays = arrays[:-1]
    vs = tuple(arrays[:nv])
    closure = tuple(arrays[nv:])

    def pick(slots, vals):
        return tuple(vals[i] if k == "v" else closure[i]
                     for k, i in slots)

    if n_out == 0 and not train:
        # fast path: no per-step outputs to pad means the result shape is
        # iteration-count independent, so a TRUE `lax.while_loop` applies —
        # cost scales with ACTUAL iterations, not max_iterations (the
        # masked scan below runs the full static trip count even when the
        # condition fails on step 1).  Inference only: while_loop has no
        # reverse-mode derivative, training keeps the differentiable scan.
        def w_cond(carry):
            vals, i, k = carry
            (c,), _ = cfn(pick(c_slots, vals), (),
                          jax.random.fold_in(k, 0))
            return jnp.logical_and(i < max_iter, jnp.squeeze(c) != 0)

        def w_body(carry):
            vals, i, k = carry
            k, fk = jax.random.split(k)
            outs, _ = ffn(pick(f_slots, vals), (), fk)
            return (tuple(outs), i + 1, k)

        fin_vals, _, _ = jax.lax.while_loop(
            w_cond, w_body, (vs, jnp.int32(0), key))
        return tuple(fin_vals)

    def body(carry, _):
        vals, active, k = carry
        k, ck, fk = jax.random.split(k, 3)
        (c,), _ = cfn(pick(c_slots, vals), (), ck)
        active = jnp.logical_and(active, jnp.squeeze(c) != 0)

        # func runs UNDER lax.cond, exactly like the reference stops
        # executing when cond fails — masking its outputs with where()
        # instead would both waste the iterations and poison gradients
        # when a terminated-range step computes inf/NaN (where's vjp
        # multiplies the NaN cotangent by zero -> NaN)
        def run(vs):
            outs, _ = ffn(pick(f_slots, vs), (), fk)
            return tuple(outs[:n_out]), tuple(outs[n_out:])

        out_shapes = jax.eval_shape(lambda vs: run(vs)[0], vals)

        def skip(vs):
            return tuple(jnp.zeros(s.shape, s.dtype)
                         for s in out_shapes), vs

        step_out, new_vals = jax.lax.cond(active, run, skip, vals)
        return (new_vals, active, k), step_out

    (fin_vals, _, _), ys = jax.lax.scan(
        body, (vs, jnp.bool_(True), key), None, length=max_iter)
    return tuple(ys) + tuple(fin_vals)


_COND_PARAMS = {
    "num_args": REQUIRED, "then_subgraph": REQUIRED, "else_subgraph": REQUIRED,
    "then_arg_map": REQUIRED, "else_arg_map": REQUIRED,
    "num_outputs": REQUIRED,
}


@register("_cond", nin=-1, variadic_param="num_args",
          params=_COND_PARAMS,
          param_types={"then_subgraph": _json_str,
                       "else_subgraph": _json_str},
          nout=lambda p: int(p["num_outputs"]),
          needs_rng=True, mode_dependent=True)
def _cond(params, *arrays):
    """reference control_flow.cc `_cond` lowered to `lax.cond`: one branch
    executes on device (the reference fetches pred to the host and runs a
    CachedOp; here the branch select stays in-program — no host sync)."""
    train = bool(params.get("_train", False))
    tfn, t_names = _sub_eval(params["then_subgraph"], train)
    efn, e_names = _sub_eval(params["else_subgraph"], train)
    t_slots = _binder(t_names, params["then_arg_map"])
    e_slots = _binder(e_names, params["else_arg_map"])
    key = arrays[-1]
    pred = arrays[0]
    closure = tuple(arrays[1:-1])

    def pick(slots):
        return tuple(closure[i] for _k, i in slots)

    def then_b(k):
        outs, _ = tfn(pick(t_slots), (), k)
        return tuple(outs)

    def else_b(k):
        outs, _ = efn(pick(e_slots), (), k)
        return tuple(outs)

    return jax.lax.cond(jnp.squeeze(pred) != 0, then_b, else_b, key)
