"""Attention operators for the transformer LM workload.

One registered op, ``BlockwiseAttention``: multi-head scaled-dot-product
attention over packed ``(batch, time, channels)`` activations, lowered
through `parallel/ring_attention.blockwise_attention` — the flash-style
online-softmax recurrence that never materializes the (T, T) score
matrix.  The projections around it (qkv, out_proj) stay ordinary
`FullyConnected` nodes so the megatron sharding rules
(`parallel/tensor_parallel.ShardingRules.megatron`) see them by name and
the mxcost dot-class rules price them; this op prices only the
score/value contractions it owns via `cost_meta`.

Registering the op here (rather than hiding the attention math inside a
gluon block) keeps saved LM symbol JSON self-describing: a checkpoint's
``*-symbol.json`` round-trips through `sym.load` in a fresh process with
no llm/ import.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, REQUIRED


def _attn_flops(params, in_avals, out_avals):
    """2*B*H*T*T*D for QK^T plus the same for scores@V."""
    q = in_avals[0]
    b, t, c = (int(d) for d in q.shape[-3:])
    return 4.0 * b * t * t * c


@register("BlockwiseAttention", nin=3,
          params={"num_heads": REQUIRED, "causal": True,
                  "block_size": None},
          input_names=["query", "key", "value"],
          cost_meta={"flops": _attn_flops})
def _blockwise_attention(params, q, k, v):
    """Multi-head attention on (B, T, C) inputs.

    Splits channels into ``num_heads`` heads, runs the blockwise exact-
    softmax recurrence, and re-packs.  ``block_size=None`` lets the
    kernel pick its tile; ``causal`` masks future positions.
    """
    from ..parallel.ring_attention import blockwise_attention
    heads = int(params["num_heads"])
    causal = bool(params.get("causal", True))
    block_size = params.get("block_size")
    if block_size is not None:
        block_size = int(block_size)
    b, t, c = q.shape[-3], q.shape[-2], q.shape[-1]
    if c % heads:
        from ..base import MXNetError
        raise MXNetError(
            "BlockwiseAttention: channels (%d) not divisible by "
            "num_heads (%d)" % (c, heads))
    d = c // heads

    def split(x):
        return x.reshape(b, t, heads, d)

    out = blockwise_attention(split(q), split(k), split(v),
                              block_size=block_size, causal=causal)
    return out.reshape(b, t, c)


def naive_attention(q, k, v, num_heads, causal=True):
    """Reference O(T^2)-memory attention on (B, T, C) packed inputs —
    materializes the full score matrix.  The parity oracle for
    `BlockwiseAttention` (tests/test_ring_attention.py) and the naive
    lane of the bench_ops attention battery; not a registered op."""
    b, t, c = q.shape
    d = c // num_heads
    qh = q.reshape(b, t, num_heads, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, t, num_heads, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, t, num_heads, d).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / jnp.sqrt(
        jnp.asarray(d, dtype=q.dtype))
    if causal:
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.asarray(-1e30, dtype=scores.dtype))
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, t, c)
