"""Linear-algebra ops (reference `src/operator/tensor/la_op.cc`).

BLAS3/LAPACK family: gemm, gemm2, potrf, potri, trsm, trmm, syrk, gelqf,
syevd, sumlogdiag, extractdiag/maketrian-style helpers are served by XLA's
native decompositions (cholesky/qr/eigh lower to TPU-supported HLOs).
Batch dimensions: all ops operate on the last two axes (as the reference).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register


@register("linalg_gemm", nin=3,
          params={"transpose_a": False, "transpose_b": False, "alpha": 1.0,
                  "beta": 1.0, "axis": -2})
def _linalg_gemm(params, a, b, c):
    ta, tb = params["transpose_a"], params["transpose_b"]
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return float(params["alpha"]) * jnp.matmul(a, b) + float(params["beta"]) * c


@register("linalg_gemm2", nin=2,
          params={"transpose_a": False, "transpose_b": False, "alpha": 1.0,
                  "axis": -2})
def _linalg_gemm2(params, a, b):
    ta, tb = params["transpose_a"], params["transpose_b"]
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return float(params["alpha"]) * jnp.matmul(a, b)


@register("linalg_potrf", nin=1)
def _linalg_potrf(params, a):
    return jnp.linalg.cholesky(a)


@register("linalg_potri", nin=1)
def _linalg_potri(params, a):
    """Inverse of A = L L^T given its Cholesky factor L (reference la_op potri)."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype),
                           a.shape[:-2] + (a.shape[-1], a.shape[-1]))
    linv = jsl.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_trsm", nin=2,
          params={"transpose": False, "rightside": False, "lower": True,
                  "alpha": 1.0})
def _linalg_trsm(params, a, b):
    alpha = float(params["alpha"])
    trans = params["transpose"]
    lower = params["lower"]
    if params["rightside"]:
        # solve X A = alpha B  <=>  A^T X^T = alpha B^T
        xt = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                  jnp.swapaxes(b, -1, -2) * alpha,
                                  lower=not lower if not trans else lower,
                                  trans=0 if not trans else 0)
        if trans:
            xt = jsl.solve_triangular(a, jnp.swapaxes(b, -1, -2) * alpha,
                                      lower=lower)
        return jnp.swapaxes(xt, -1, -2)
    return jsl.solve_triangular(a, b * alpha, lower=lower,
                                trans=1 if trans else 0)


@register("linalg_trmm", nin=2,
          params={"transpose": False, "rightside": False, "lower": True,
                  "alpha": 1.0})
def _linalg_trmm(params, a, b):
    alpha = float(params["alpha"])
    tri = jnp.tril(a) if params["lower"] else jnp.triu(a)
    if params["transpose"]:
        tri = jnp.swapaxes(tri, -1, -2)
    if params["rightside"]:
        return alpha * jnp.matmul(b, tri)
    return alpha * jnp.matmul(tri, b)


@register("linalg_syrk", nin=1, params={"transpose": False, "alpha": 1.0})
def _linalg_syrk(params, a):
    at = jnp.swapaxes(a, -1, -2)
    if params["transpose"]:
        return float(params["alpha"]) * jnp.matmul(at, a)
    return float(params["alpha"]) * jnp.matmul(a, at)


@register("linalg_gelqf", nin=1, nout=2)
def _linalg_gelqf(params, a):
    """LQ factorization A = L Q (reference la_op gelqf) via QR of A^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd", nin=1, nout=2)
def _linalg_syevd(params, a):
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_sumlogdiag", nin=1)
def _linalg_sumlogdiag(params, a):
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("linalg_extractdiag", nin=1, params={"offset": 0})
def _linalg_extractdiag(params, a):
    return jnp.diagonal(a, offset=int(params["offset"]), axis1=-2, axis2=-1)


@register("linalg_makediag", nin=1, params={"offset": 0})
def _linalg_makediag(params, a):
    k = int(params["offset"])
    n = a.shape[-1] + abs(k)
    out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
    idx = jnp.arange(a.shape[-1])
    if k >= 0:
        return out.at[..., idx, idx + k].set(a)
    return out.at[..., idx - k, idx].set(a)


@register("linalg_extracttrian", nin=1, params={"offset": 0, "lower": True})
def _linalg_extracttrian(params, a):
    """Reference la_op extracttrian: pack the triangle at diagonal ``offset``
    (lower: offset <= 0 moves below the diagonal; upper: offset >= 0 above)."""
    n = a.shape[-1]
    k = int(params["offset"])
    if params["lower"]:
        ii, jj = jnp.tril_indices(n, k=k)
    else:
        ii, jj = jnp.triu_indices(n, k=k)
    return a[..., ii, jj]


@register("linalg_inverse", nin=1)
def _linalg_inverse(params, a):
    return jnp.linalg.inv(a)


@register("linalg_det", nin=1)
def _linalg_det(params, a):
    return jnp.linalg.det(a)


@register("linalg_slogdet", nin=1, nout=2)
def _linalg_slogdet(params, a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet
