"""Elementwise operators: unary, binary (broadcast + same-shape), scalar, logic.

Covers the reference families in `src/operator/tensor/`:
`elemwise_unary_op_basic.cc`, `elemwise_unary_op_trig.cc`,
`elemwise_binary_broadcast_op_{basic,extended,logic}.cc`,
`elemwise_binary_op_basic.cc`, `elemwise_binary_scalar_op_*.cc`.

Every op is one jax-traceable function; XLA fuses chains of these into single
TPU kernels (the mshadow expression-template fusion equivalent, done by the
compiler instead of C++ templates).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

# ---------------------------------------------------------------------------
# Unary
# ---------------------------------------------------------------------------

_UNARY = {
    # name: (fn, aliases)
    "abs": (jnp.abs, ("_abs",)),
    "sign": (jnp.sign, ()),
    "rint": (jnp.rint, ()),
    "round": (jnp.round, ()),
    "ceil": (jnp.ceil, ()),
    "floor": (jnp.floor, ()),
    "trunc": (jnp.trunc, ()),
    "fix": (jnp.trunc, ()),
    "square": (jnp.square, ()),
    "sqrt": (jnp.sqrt, ()),
    "rsqrt": (lambda x: jax.lax.rsqrt(x), ()),
    "cbrt": (jnp.cbrt, ()),
    "rcbrt": (lambda x: 1.0 / jnp.cbrt(x), ()),
    "exp": (jnp.exp, ()),
    "log": (jnp.log, ()),
    "log10": (jnp.log10, ()),
    "log2": (jnp.log2, ()),
    "log1p": (jnp.log1p, ()),
    "expm1": (jnp.expm1, ()),
    "sin": (jnp.sin, ()),
    "cos": (jnp.cos, ()),
    "tan": (jnp.tan, ()),
    "arcsin": (jnp.arcsin, ()),
    "arccos": (jnp.arccos, ()),
    "arctan": (jnp.arctan, ()),
    "sinh": (jnp.sinh, ()),
    "cosh": (jnp.cosh, ()),
    "tanh": (jnp.tanh, ()),
    "arcsinh": (jnp.arcsinh, ()),
    "arccosh": (jnp.arccosh, ()),
    "arctanh": (jnp.arctanh, ()),
    "degrees": (jnp.degrees, ()),
    "radians": (jnp.radians, ()),
    "sigmoid": (jax.nn.sigmoid, ()),
    "softsign": (jax.nn.soft_sign, ()),
    "relu": (jax.nn.relu, ()),
    "reciprocal": (lambda x: 1.0 / x, ()),
    "erf": (jax.scipy.special.erf, ()),
    "erfinv": (jax.scipy.special.erfinv, ()),
    "gammaln": (jax.scipy.special.gammaln, ()),
    "logical_not": (lambda x: (x == 0).astype(x.dtype), ()),
    "negative": (jnp.negative, ("_np_negative",)),
}


def _make_unary(f):
    def fn(params, x):
        return f(x)
    return fn


for _name, (_f, _aliases) in _UNARY.items():
    register(_name, nin=1, aliases=_aliases)(_make_unary(_f))


@register("gamma")
def _gamma(params, x):
    """tgamma (reference `elemwise_unary_op_basic.cc` gamma)."""
    try:
        return jax.scipy.special.gamma(x)
    except AttributeError:  # older jax
        return jnp.exp(jax.scipy.special.gammaln(x)) * jnp.where(
            (x < 0) & (jnp.floor(x / 2) * 2 != jnp.floor(x)), -1.0, 1.0)


@register("_copy", aliases=("identity",))
def _copy(params, x):
    return x + 0 if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


@register("BlockGrad", aliases=("stop_gradient", "block_grad"), stop_grad=True)
def _block_grad(params, x):
    """Reference `src/operator/tensor/elemwise_unary_op_basic.cc` BlockGrad."""
    return jax.lax.stop_gradient(x)


@register("make_loss", aliases=("MakeLoss_simple",))
def _make_loss(params, x):
    return x


@register("zeros_like")
def _zeros_like(params, x):
    return jnp.zeros_like(x)


@register("ones_like")
def _ones_like(params, x):
    return jnp.ones_like(x)


@register("clip", params={"a_min": None, "a_max": None})
def _clip(params, x):
    """Reference `src/operator/tensor/matrix_op.cc` clip."""
    return jnp.clip(x, params["a_min"], params["a_max"])


# ---------------------------------------------------------------------------
# Binary with broadcasting (reference broadcast_* family) and same-shape
# elemwise_* family.  jnp broadcasts by numpy rules which subsume mshadow's.
# ---------------------------------------------------------------------------

def _cmp(f):
    def g(x, y):
        return f(x, y).astype(jnp.result_type(x, y))
    return g


_BINARY = {
    "broadcast_add": (jnp.add, ("broadcast_plus", "elemwise_add", "_add", "_plus", "_Plus")),
    "broadcast_sub": (jnp.subtract, ("broadcast_minus", "elemwise_sub", "_sub", "_minus", "_Minus")),
    "broadcast_mul": (jnp.multiply, ("elemwise_mul", "_mul", "_Mul")),
    "broadcast_div": (jnp.divide, ("elemwise_div", "_div", "_Div")),
    "broadcast_mod": (jnp.mod, ("_mod",)),
    "broadcast_power": (jnp.power, ("_power", "_Power", "pow")),
    "broadcast_maximum": (jnp.maximum, ("_maximum",)),
    "broadcast_minimum": (jnp.minimum, ("_minimum",)),
    "broadcast_hypot": (jnp.hypot, ("_hypot",)),
    "broadcast_equal": (_cmp(jnp.equal), ("_equal",)),
    "broadcast_not_equal": (_cmp(jnp.not_equal), ("_not_equal",)),
    "broadcast_greater": (_cmp(jnp.greater), ("_greater",)),
    "broadcast_greater_equal": (_cmp(jnp.greater_equal), ("_greater_equal",)),
    "broadcast_lesser": (_cmp(jnp.less), ("_lesser",)),
    "broadcast_lesser_equal": (_cmp(jnp.less_equal), ("_lesser_equal",)),
    "broadcast_logical_and": (_cmp(jnp.logical_and), ("_logical_and",)),
    "broadcast_logical_or": (_cmp(jnp.logical_or), ("_logical_or",)),
    "broadcast_logical_xor": (_cmp(jnp.logical_xor), ("_logical_xor",)),
}


def _make_binary(f):
    def fn(params, x, y):
        return f(x, y)
    return fn


for _name, (_f, _aliases) in _BINARY.items():
    register(_name, nin=2, aliases=_aliases)(_make_binary(_f))


@register("smooth_l1", nin=1, params={"scalar": 1.0})
def _smooth_l1(params, x):
    """Reference `elemwise_binary_scalar_op_extended.cc` smooth_l1."""
    s2 = float(params["scalar"]) ** 2
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * jnp.square(x), ax - 0.5 / s2)


# ---------------------------------------------------------------------------
# Scalar ops (reference elemwise_binary_scalar_op_*.cc) — scalar is a static
# attr in the reference; we keep it static too so the jit cache keys on it.
# ---------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(x, s).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(x, s).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(x, s).astype(x.dtype),
}


def _make_scalar(f):
    def fn(params, x):
        return f(x, params["scalar"])
    return fn


from .registry import REQUIRED  # noqa: E402

for _name, _f in _SCALAR.items():
    register(_name, nin=1, params={"scalar": REQUIRED})(_make_scalar(_f))
