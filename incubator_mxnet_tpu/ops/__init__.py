"""Operator library.

TPU-native equivalent of `src/operator/` (reference, 113.7 kLoC C++/CUDA):
each module registers pure jax-traceable compute functions with the central
registry (`registry.py`); XLA compiles them to TPU kernels, so there are no
per-backend kernel files.  Frontend namespaces (`nd.*`, `sym.*`) are generated
from this registry at import, like the reference generates Python ops from
`MXSymbolListAtomicSymbolCreators`.
"""
from . import registry
from .registry import register, get, list_ops, OpDef, REQUIRED

# op definition modules — import order only matters for alias collisions
from . import elemwise      # noqa: F401
from . import reduce        # noqa: F401
from . import matrix        # noqa: F401
from . import init_ops      # noqa: F401
from . import random_ops    # noqa: F401
from . import nn            # noqa: F401
from . import attention     # noqa: F401
from . import loss_output   # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import linalg_ops    # noqa: F401
from . import contrib_ops   # noqa: F401
from . import ctc           # noqa: F401
from . import detection     # noqa: F401
from . import spatial       # noqa: F401
from . import image_ops     # noqa: F401
from . import control_flow  # noqa: F401
from . import contrib_tail  # noqa: F401
from . import quantization  # noqa: F401
