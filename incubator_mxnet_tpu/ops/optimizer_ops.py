"""Fused optimizer update ops.

Reference: `src/operator/optimizer_op.cc` (+`optimizer_op-inl.h`): sgd_update,
sgd_mom_update, mp_sgd_* (fp16 weights with fp32 master copies — on TPU the
analogue is bf16 weights + fp32 masters), adam, rmsprop, rmspropalex, ftrl,
signsgd, signum.

Semantics: ops return the new weight (written to ``out=weight`` by callers,
matching the reference's in-place kWriteInplace) and update their state
tensors (momentum/mean/var/…) as aux outputs written back in place.
``lr``/``wd``/``rescale_grad``/``clip_gradient`` are *dynamic* scalar inputs so
learning-rate schedules do not retrigger XLA compilation (OpDef.dynamic_params).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

_DYN = ("lr", "wd", "rescale_grad", "clip_gradient")
_COMMON = {"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0,
           "lazy_update": True}


def _prep_grad(grad, rescale, clip):
    g = grad * rescale
    return jnp.where(clip > 0, jnp.clip(g, -jnp.abs(clip), jnp.abs(clip)), g)


@register("sgd_update", nin=2, params=dict(_COMMON), dynamic_params=_DYN)
def _sgd_update(params, weight, grad, lr, wd, rescale, clip):
    g = _prep_grad(grad, rescale, clip).astype(weight.dtype)
    lr = lr.astype(weight.dtype)
    wd = wd.astype(weight.dtype)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", nin=3, naux=1, params={**_COMMON, "momentum": 0.0},
          dynamic_params=_DYN)
def _sgd_mom_update(params, weight, grad, mom, lr, wd, rescale, clip):
    mu = float(params["momentum"])
    g = _prep_grad(grad, rescale, clip).astype(weight.dtype)
    lr = lr.astype(weight.dtype)
    wd = wd.astype(weight.dtype)
    new_mom = mu * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", nin=3, naux=1, params=dict(_COMMON), dynamic_params=_DYN)
def _mp_sgd_update(params, weight, grad, weight32, lr, wd, rescale, clip):
    """Multi-precision SGD: grads applied to the fp32 master copy, low-precision
    weight refreshed from it (reference optimizer_op-inl.h MP_SGDKernel)."""
    g = _prep_grad(grad.astype("float32"), rescale, clip)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", nin=4, naux=2,
          params={**_COMMON, "momentum": 0.0}, dynamic_params=_DYN)
def _mp_sgd_mom_update(params, weight, grad, mom, weight32, lr, wd, rescale, clip):
    mu = float(params["momentum"])
    g = _prep_grad(grad.astype("float32"), rescale, clip)
    new_mom = mu * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", nin=4, naux=2,
          params={**_COMMON, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
          dynamic_params=_DYN)
def _adam_update(params, weight, grad, mean, var, lr, wd, rescale, clip):
    b1, b2 = float(params["beta1"]), float(params["beta2"])
    eps = float(params["epsilon"])
    g = _prep_grad(grad, rescale, clip).astype(weight.dtype) + \
        wd.astype(weight.dtype) * weight
    new_mean = b1 * mean + (1 - b1) * g
    new_var = b2 * var + (1 - b2) * jnp.square(g)
    new_w = weight - lr.astype(weight.dtype) * new_mean / (jnp.sqrt(new_var) + eps)
    return new_w, new_mean, new_var


@register("rmsprop_update", nin=3, naux=1,
          params={**_COMMON, "gamma1": 0.95, "epsilon": 1e-8}, dynamic_params=_DYN)
def _rmsprop_update(params, weight, grad, n, lr, wd, rescale, clip):
    g1 = float(params["gamma1"])
    eps = float(params["epsilon"])
    g = _prep_grad(grad, rescale, clip) + wd * weight
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + eps)
    return new_w, new_n


@register("rmspropalex_update", nin=5, naux=3,
          params={**_COMMON, "gamma1": 0.95, "gamma2": 0.9, "epsilon": 1e-8},
          dynamic_params=_DYN)
def _rmspropalex_update(params, weight, grad, n, g_avg, delta, lr, wd, rescale, clip):
    g1, g2 = float(params["gamma1"]), float(params["gamma2"])
    eps = float(params["epsilon"])
    g = _prep_grad(grad, rescale, clip) + wd * weight
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_g = (1 - g1) * g + g1 * g_avg
    new_delta = g2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + eps)
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", nin=4, naux=2,
          params={**_COMMON, "lamda1": 0.01, "beta": 1.0}, dynamic_params=_DYN)
def _ftrl_update(params, weight, grad, z, n, lr, wd, rescale, clip):
    l1 = float(params["lamda1"])
    beta = float(params["beta"])
    g = _prep_grad(grad, rescale, clip)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > l1,
        -(new_z - jnp.sign(new_z) * l1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        jnp.zeros_like(weight))
    return new_w, new_z, new_n


@register("signsgd_update", nin=2, params=dict(_COMMON), dynamic_params=_DYN)
def _signsgd_update(params, weight, grad, lr, wd, rescale, clip):
    g = _prep_grad(grad, rescale, clip)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", nin=3, naux=1,
          params={**_COMMON, "momentum": 0.0, "wd_lh": 0.0}, dynamic_params=_DYN)
def _signum_update(params, weight, grad, mom, lr, wd, rescale, clip):
    mu = float(params["momentum"])
    wd_lh = float(params["wd_lh"])
    g = _prep_grad(grad, rescale, clip)
    new_mom = mu * mom - (1 - mu) * (g + wd * weight)
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom
