"""Spatial-warp operators (reference `src/operator/bilinear_sampler.cc`,
`grid_generator.cc`, `spatial_transformer.cc`, `correlation.cc`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED
from ..base import MXNetError


def _bilinear_sample(img, gy, gx):
    """img (C, H, W); gy/gx normalized [-1, 1] grids of shape (Ho, Wo)."""
    C, H, W = img.shape
    y = (gy + 1) * (H - 1) / 2
    x = (gx + 1) * (W - 1) / 2
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def at(yi, xi):
        inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]
        return jnp.where(inb[None], v, 0.0)

    out = (at(y0, x0) * (1 - wy) * (1 - wx) +
           at(y0 + 1, x0) * wy * (1 - wx) +
           at(y0, x0 + 1) * (1 - wy) * wx +
           at(y0 + 1, x0 + 1) * wy * wx)
    return out


@register("BilinearSampler", nin=2, params={"cudnn_off": False})
def _bilinear_sampler(params, data, grid):
    """Reference bilinear_sampler.cc: grid (B, 2, Ho, Wo) with (x, y) in
    [-1, 1]."""
    def per(img, g):
        return _bilinear_sample(img, g[1], g[0])
    return jax.vmap(per)(data, grid)


@register("GridGenerator", nin=1,
          params={"transform_type": REQUIRED, "target_shape": (0, 0)})
def _grid_generator(params, data):
    """Reference grid_generator.cc: affine (B, 6) -> sampling grid, or warp
    flow (B, 2, H, W) -> grid."""
    tt = params["transform_type"]
    th, tw = tuple(params["target_shape"])
    if tt == "affine":
        B = data.shape[0]
        ys = jnp.linspace(-1, 1, th)
        xs = jnp.linspace(-1, 1, tw)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.reshape(-1), gy.reshape(-1), ones.reshape(-1)])

        def per(theta):
            m = theta.reshape(2, 3)
            out = m @ base                   # (2, th*tw)
            return out.reshape(2, th, tw)

        return jax.vmap(per)(data)
    if tt == "warp":
        B, _, H, W = data.shape
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x = (data[:, 0] + gx[None]) * 2 / jnp.maximum(W - 1, 1) - 1
        y = (data[:, 1] + gy[None]) * 2 / jnp.maximum(H - 1, 1) - 1
        return jnp.stack([x, y], axis=1)
    raise MXNetError(f"GridGenerator: bad transform_type {tt}")


@register("SpatialTransformer", nin=2,
          params={"target_shape": (0, 0), "transform_type": "affine",
                  "sampler_type": "bilinear", "cudnn_off": False})
def _spatial_transformer(params, data, loc):
    """Reference spatial_transformer.cc: affine theta (B, 6) + bilinear."""
    th, tw = tuple(params["target_shape"])
    grid = _grid_generator({"transform_type": "affine",
                            "target_shape": (th, tw)}, loc)

    def per(img, g):
        return _bilinear_sample(img, g[1], g[0])

    return jax.vmap(per)(data, grid)


@register("Correlation", nin=2,
          params={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                  "stride2": 1, "pad_size": 0, "is_multiply": True})
def _correlation(params, data1, data2):
    """Reference correlation.cc (FlowNet-style cost volume)."""
    k = int(params["kernel_size"])
    md = int(params["max_displacement"])
    s1 = int(params["stride1"])
    s2 = int(params["stride2"])
    pad = int(params["pad_size"])
    mult = bool(params["is_multiply"])
    B, C, H, W = data1.shape
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d_range = range(-md, md + 1, s2)
    outs = []
    Hp, Wp = H + 2 * pad, W + 2 * pad
    for dy in d_range:
        for dx in d_range:
            a = p1[:, :, md:Hp - md, md:Wp - md]
            b = p2[:, :, md + dy:Hp - md + dy, md + dx:Wp - md + dx]
            if mult:
                corr = jnp.mean(a * b, axis=1)
            else:
                corr = jnp.mean(jnp.abs(a - b), axis=1)
            outs.append(corr[:, ::s1, ::s1])
    return jnp.stack(outs, axis=1)


@register("Crop", nin=-1,
          params={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                  "center_crop": False}, variadic_param="num_args")
def _crop_op(params, *args):
    """Reference crop.cc: crop first input to second's spatial size (or h_w)."""
    data = args[0]
    if len(args) > 1:
        h, w = args[1].shape[2], args[1].shape[3]
    else:
        h, w = tuple(params["h_w"])
    if params["center_crop"]:
        oy = (data.shape[2] - h) // 2
        ox = (data.shape[3] - w) // 2
    else:
        oy, ox = tuple(params["offset"])
    return data[:, :, oy:oy + h, ox:ox + w]
