"""Creation ops (reference `src/operator/tensor/init_op.cc`)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, REQUIRED


@register("_zeros", nin=0, params={"shape": (), "dtype": "float32"})
def _zeros(params):
    return jnp.zeros(tuple(params["shape"]), dtype=params["dtype"] or "float32")


@register("_ones", nin=0, params={"shape": (), "dtype": "float32"})
def _ones(params):
    return jnp.ones(tuple(params["shape"]), dtype=params["dtype"] or "float32")


@register("_full", nin=0, params={"shape": (), "dtype": "float32", "value": REQUIRED})
def _full(params):
    return jnp.full(tuple(params["shape"]), params["value"],
                    dtype=params["dtype"] or "float32")


@register("_arange", nin=0,
          params={"start": 0.0, "stop": None, "step": 1.0, "repeat": 1,
                  "infer_range": False, "dtype": "float32"})
def _arange(params):
    out = jnp.arange(params["start"], params["stop"], params["step"],
                     dtype=params["dtype"] or "float32")
    if int(params["repeat"]) > 1:
        out = jnp.repeat(out, int(params["repeat"]))
    return out


@register("_eye", nin=0, params={"N": REQUIRED, "M": 0, "k": 0, "dtype": "float32"})
def _eye(params):
    n = int(params["N"])
    m = int(params["M"]) or n
    return jnp.eye(n, m, k=int(params["k"]), dtype=params["dtype"] or "float32")


@register("_linspace", nin=0,
          params={"start": REQUIRED, "stop": REQUIRED, "num": REQUIRED,
                  "endpoint": True, "dtype": "float32"})
def _linspace(params):
    return jnp.linspace(params["start"], params["stop"], int(params["num"]),
                        endpoint=bool(params["endpoint"]),
                        dtype=params["dtype"] or "float32")
