"""Quantization ops (reference `src/operator/quantization/` —
quantize.cc, dequantize.cc, requantize.cc, quantized_conv/fc/pooling,
calibration via min/max).

INT8 inference path: values quantized symmetric/affine into int8 with
min/max ranges carried alongside (the reference's 3-tensor convention).
Quantized compute ops dequantize-compute-requantize through XLA int8/int32
matmul where profitable; the graph rewrite lives in
`incubator_mxnet_tpu/contrib/quantization.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED


@register("_contrib_quantize", nin=3, nout=3, params={"out_type": "int8"},
          aliases=("quantize",))
def _quantize(params, data, min_range, max_range):
    """Reference quantize.cc: float -> int8 with given range."""
    q_min, q_max = -127.0, 127.0
    scale = jnp.maximum(jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)),
                        1e-8)
    out = jnp.clip(jnp.round(data / scale * q_max), q_min, q_max) \
        .astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_quantize_v2", nin=1, nout=3,
          params={"out_type": "int8", "min_calib_range": None,
                  "max_calib_range": None})
def _quantize_v2(params, data):
    if params["min_calib_range"] is not None:
        mn = jnp.asarray(params["min_calib_range"], jnp.float32)
        mx = jnp.asarray(params["max_calib_range"], jnp.float32)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8)
    out = jnp.clip(jnp.round(data / scale * 127.0), -127, 127).astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_dequantize", nin=3, params={"out_type": "float32"},
          aliases=("dequantize",))
def _dequantize(params, data, min_range, max_range):
    scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * scale / 127.0


@register("_contrib_requantize", nin=3, nout=3,
          params={"out_type": "int8", "min_calib_range": None,
                  "max_calib_range": None})
def _requantize(params, data, min_range, max_range):
    """int32 accumulators -> int8 (reference requantize.cc)."""
    real = data.astype(jnp.float32) * jnp.maximum(
        jnp.abs(min_range), jnp.abs(max_range)) / (127.0 * 127.0)
    if params["min_calib_range"] is not None:
        mn = jnp.asarray(params["min_calib_range"], jnp.float32)
        mx = jnp.asarray(params["max_calib_range"], jnp.float32)
    else:
        mn = jnp.min(real)
        mx = jnp.max(real)
    scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8)
    out = jnp.clip(jnp.round(real / scale * 127.0), -127, 127).astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_quantized_fully_connected", nin=-1, nout=3,
          params={"num_hidden": REQUIRED, "no_bias": False, "flatten": True})
def _quantized_fc(params, *args):
    """int8 x int8 -> int32 matmul (reference quantized_fully_connected.cc).
    Inputs: data, weight, [bias], min/max for each."""
    no_bias = bool(params["no_bias"])
    if no_bias:
        data, weight, dmin, dmax, wmin, wmax = args
        bias = None
    else:
        data, weight, bias, dmin, dmax, wmin, wmax, bmin, bmax = args
    x = data.astype(jnp.int32)
    if params["flatten"]:
        x = x.reshape(x.shape[0], -1)
    out = jax.lax.dot(x, weight.astype(jnp.int32).T)
    if bias is not None:
        out = out + bias.astype(jnp.int32)
    d_scale = jnp.maximum(jnp.abs(dmin), jnp.abs(dmax)) / 127.0
    w_scale = jnp.maximum(jnp.abs(wmin), jnp.abs(wmax)) / 127.0
    out_range = d_scale * w_scale * 127.0 * 127.0
    return out, -out_range, out_range
