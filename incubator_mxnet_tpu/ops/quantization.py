"""Quantization ops (reference `src/operator/quantization/` —
quantize.cc, dequantize.cc, requantize.cc, quantized_conv/fc/pooling,
calibration via min/max).

INT8 inference path: values quantized symmetric/affine into int8 with
min/max ranges carried alongside (the reference's 3-tensor convention).
Quantized compute ops dequantize-compute-requantize through XLA int8/int32
matmul where profitable; the graph rewrite lives in
`incubator_mxnet_tpu/contrib/quantization.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED

# Static cost metadata (OpDef.cost_meta) for the mxcost analyzer
# (analysis/cost.py).  The honest declaration matters more than the
# numbers: every quantized compute op below runs its arithmetic in
# float32 on this design (see the _quantized_conv docstring), so each
# declares ``compute_dtype="float32"`` — which is exactly the static
# signature mxcost's dtype-flow pass flags as the int8-slower-than-fp32
# defect (BENCH_OPS: int8 convnet 1.8x slower).  When the lowering
# moves to native XLA int8 dot/conv with fused epilogues (ROADMAP open
# item 4), these declarations change to "int8" and the findings — and
# the CI budget gate holding their count — retire with the defect.
_QUANT_ELEMWISE = {"quantized": True, "compute_dtype": "float32"}
_QUANT_COMPUTE = {"quantized": True, "compute_dtype": "float32"}


@register("_contrib_quantize", nin=3, nout=3, params={"out_type": "int8"},
          aliases=("quantize",), cost_meta=_QUANT_ELEMWISE)
def _quantize(params, data, min_range, max_range):
    """Reference quantize.cc: float -> int8 with given range."""
    q_min, q_max = -127.0, 127.0
    scale = jnp.maximum(jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)),
                        1e-8)
    out = jnp.clip(jnp.round(data / scale * q_max), q_min, q_max) \
        .astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_quantize_v2", nin=1, nout=3,
          params={"out_type": "int8", "min_calib_range": None,
                  "max_calib_range": None}, cost_meta=_QUANT_ELEMWISE)
def _quantize_v2(params, data):
    if params["min_calib_range"] is not None:
        mn = jnp.asarray(params["min_calib_range"], jnp.float32)
        mx = jnp.asarray(params["max_calib_range"], jnp.float32)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8)
    out = jnp.clip(jnp.round(data / scale * 127.0), -127, 127).astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_dequantize", nin=3, params={"out_type": "float32"},
          aliases=("dequantize",), cost_meta=_QUANT_ELEMWISE)
def _dequantize(params, data, min_range, max_range):
    """int8 carries real = q * range/127; int32 accumulators from quantized
    matmul/conv carry real = q * range/127^2 (reference dequantizes int32
    through requantize first — this op accepts both directly)."""
    scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    q_max = 127.0 if data.dtype == jnp.int8 else 127.0 * 127.0
    return data.astype(jnp.float32) * scale / q_max


@register("_contrib_requantize", nin=3, nout=3,
          params={"out_type": "int8", "min_calib_range": None,
                  "max_calib_range": None}, cost_meta=_QUANT_ELEMWISE)
def _requantize(params, data, min_range, max_range):
    """int32 accumulators -> int8 (reference requantize.cc)."""
    real = data.astype(jnp.float32) * jnp.maximum(
        jnp.abs(min_range), jnp.abs(max_range)) / (127.0 * 127.0)
    if params["min_calib_range"] is not None:
        mn = jnp.asarray(params["min_calib_range"], jnp.float32)
        mx = jnp.asarray(params["max_calib_range"], jnp.float32)
    else:
        mn = jnp.min(real)
        mx = jnp.max(real)
    scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8)
    out = jnp.clip(jnp.round(real / scale * 127.0), -127, 127).astype(jnp.int8)
    return out, -scale, scale


@register("_contrib_quantized_fully_connected", nin=-1, nout=3,
          params={"num_hidden": REQUIRED, "no_bias": False, "flatten": True},
          cost_meta=_QUANT_COMPUTE)
def _quantized_fc(params, *args):
    """int8 x int8 -> int32 matmul (reference quantized_fully_connected.cc).
    Inputs: data, weight, [bias], min/max for each."""
    no_bias = bool(params["no_bias"])
    if no_bias:
        data, weight, dmin, dmax, wmin, wmax = args
        bias = None
    else:
        data, weight, bias, dmin, dmax, wmin, wmax, bmin, bmax = args
    x = data.astype(jnp.int32)
    if params["flatten"]:
        x = x.reshape(x.shape[0], -1)
    out = jax.lax.dot(x, weight.astype(jnp.int32).T)
    d_scale = jnp.maximum(jnp.abs(dmin), jnp.abs(dmax)) / 127.0
    w_scale = jnp.maximum(jnp.abs(wmin), jnp.abs(wmax)) / 127.0
    if bias is not None:
        # the int8 bias carries its OWN scale (b_scale); accumulators carry
        # d_scale*w_scale — rescale into accumulator units before adding
        # (reference quantized_fully_connected float_for_one_quant_of_bias)
        b_scale = jnp.maximum(jnp.abs(bmin), jnp.abs(bmax)) / 127.0
        bias_acc = jnp.round(bias.astype(jnp.float32) * b_scale /
                             (d_scale * w_scale)).astype(jnp.int32)
        out = out + bias_acc
    out_range = d_scale * w_scale * 127.0 * 127.0
    return out, -out_range, out_range


def _pair(v, default=None):
    t = (v, v) if isinstance(v, int) else tuple(v)
    return t if t else (default or (1, 1))


@register("_contrib_quantized_conv", nin=-1, nout=3,
          params={"kernel": REQUIRED, "stride": (1, 1), "pad": (0, 0),
                  "dilate": (1, 1), "num_filter": REQUIRED, "num_group": 1,
                  "no_bias": False, "layout": "NCHW"},
          cost_meta=_QUANT_COMPUTE)
def _quantized_conv(params, *args):
    """int8 conv -> int32 accumulators (reference quantized_conv.cc).

    Arithmetic runs in f32 and is rounded back: int8 products are <= 127^2
    and partial sums stay inside f32's exact-integer window for any
    practical kernel volume, and f32 convs map onto the TPU MXU where
    int accumulation would not.
    """
    no_bias = bool(params["no_bias"])
    if no_bias:
        data, weight, dmin, dmax, wmin, wmax = args
        bias = None
    else:
        data, weight, bias, dmin, dmax, wmin, wmax, bmin, bmax = args
    stride = _pair(params["stride"])
    pad = _pair(params["pad"], (0, 0))
    dilate = _pair(params["dilate"])
    out = jax.lax.conv_general_dilated(
        data.astype(jnp.float32), weight.astype(jnp.float32),
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilate,
        feature_group_count=int(params["num_group"]),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    out = jnp.round(out).astype(jnp.int32)
    d_scale = jnp.maximum(jnp.abs(dmin), jnp.abs(dmax)) / 127.0
    w_scale = jnp.maximum(jnp.abs(wmin), jnp.abs(wmax)) / 127.0
    if bias is not None:
        # rescale the int8 bias from its own scale into accumulator units
        # (reference quantized_conv.cu float_for_one_out_quant)
        b_scale = jnp.maximum(jnp.abs(bmin), jnp.abs(bmax)) / 127.0
        bias_acc = jnp.round(bias.astype(jnp.float32) * b_scale /
                             (d_scale * w_scale)).astype(jnp.int32)
        out = out + bias_acc.reshape(1, -1, 1, 1)
    out_range = d_scale * w_scale * 127.0 * 127.0
    return out, -out_range, out_range


@register("_contrib_quantized_pooling", nin=3, nout=3,
          params={"kernel": REQUIRED, "pool_type": "max", "stride": (1, 1),
                  "pad": (0, 0), "global_pool": False,
                  "pooling_convention": "valid"},
          cost_meta=_QUANT_ELEMWISE)
def _quantized_pooling(params, data, min_range, max_range):
    """Pooling on int8 values; ranges pass through unchanged
    (reference quantized_pooling.cc: pooling is range-preserving)."""
    ptype = params["pool_type"]
    if params["global_pool"]:
        kernel = data.shape[2:]
        stride = (1, 1)
        pad = (0, 0)
    else:
        kernel = _pair(params["kernel"])
        stride = _pair(params["stride"])
        pad = _pair(params["pad"], (0, 0))
    x = data.astype(jnp.float32)
    dims = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1]))
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides,
                                    padding)
    elif ptype == "avg":
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, padding)
        out = s / float(kernel[0] * kernel[1])
    else:
        raise ValueError(f"quantized_pooling: pool_type {ptype}")
    out = jnp.clip(jnp.round(out), -127, 127).astype(data.dtype)
    return out, min_range, max_range
