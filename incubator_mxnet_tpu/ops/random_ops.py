"""Random sampling ops (reference `src/operator/random/sample_op.cc`,
`sample_multinomial_op.cc`, `shuffle_op.cc`).

The reference keeps per-device stateful mt19937/cuRAND generators behind
ResourceManager (`src/resource.cc:87-160`).  TPU-native RNG is counter-based
(threefry): every op invocation consumes a fresh subkey from the framework's
global key chain (`incubator_mxnet_tpu.random`), passed to the kernel as a
trailing input array — statistical, not bitwise, parity with the reference
(documented divergence, SURVEY.md §7 hard part (c)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _shape(params):
    s = params.get("shape", ())
    if s is None:
        s = ()
    if isinstance(s, int):
        s = (s,)
    return tuple(s)


def _dt(params):
    d = params.get("dtype") or "float32"
    return "float32" if d in (None, "None") else d


@register("_random_uniform", nin=0, needs_rng=True, aliases=("uniform",),
          params={"low": 0.0, "high": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_uniform(params, key):
    return jax.random.uniform(key, _shape(params), dtype=_dt(params),
                              minval=params["low"], maxval=params["high"])


@register("_random_normal", nin=0, needs_rng=True, aliases=("normal",),
          params={"loc": 0.0, "scale": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_normal(params, key):
    return params["loc"] + params["scale"] * jax.random.normal(
        key, _shape(params), dtype=_dt(params))


@register("_random_gamma", nin=0, needs_rng=True, aliases=("gamma_sample",),
          params={"alpha": 1.0, "beta": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_gamma(params, key):
    return params["beta"] * jax.random.gamma(key, params["alpha"], _shape(params),
                                             dtype=_dt(params))


@register("_random_exponential", nin=0, needs_rng=True,
          params={"lam": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_exponential(params, key):
    return jax.random.exponential(key, _shape(params), dtype=_dt(params)) / params["lam"]


@register("_random_poisson", nin=0, needs_rng=True,
          params={"lam": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_poisson(params, key):
    return jax.random.poisson(key, params["lam"], _shape(params)).astype(_dt(params))


@register("_random_negative_binomial", nin=0, needs_rng=True,
          params={"k": 1, "p": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_negative_binomial(params, key):
    k1, k2 = jax.random.split(key)
    p = params["p"]
    lam = jax.random.gamma(k1, float(params["k"]), _shape(params)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(params)).astype(_dt(params))


@register("_random_generalized_negative_binomial", nin=0, needs_rng=True,
          params={"mu": 1.0, "alpha": 1.0, "shape": (), "dtype": "float32", "ctx": None})
def _random_generalized_negative_binomial(params, key):
    k1, k2 = jax.random.split(key)
    mu, alpha = params["mu"], params["alpha"]
    lam = jax.random.gamma(k1, 1.0 / alpha, _shape(params)) * (alpha * mu)
    return jax.random.poisson(k2, lam, _shape(params)).astype(_dt(params))


@register("_random_randint", nin=0, needs_rng=True,
          params={"low": 0, "high": 1, "shape": (), "dtype": "int32", "ctx": None})
def _random_randint(params, key):
    return jax.random.randint(key, _shape(params), int(params["low"]),
                              int(params["high"]),
                              dtype=params.get("dtype") or "int32")


# -- parameter-tensor variants (_sample_*): one sample row per distribution row
@register("_sample_uniform", nin=2, needs_rng=True, aliases=(),
          params={"shape": (), "dtype": "float32"})
def _sample_uniform(params, low, high, key):
    s = _shape(params)
    u = jax.random.uniform(key, low.shape + s, dtype=_dt(params))
    return low.reshape(low.shape + (1,) * len(s)) + u * (high - low).reshape(
        low.shape + (1,) * len(s))


@register("_sample_normal", nin=2, needs_rng=True,
          params={"shape": (), "dtype": "float32"})
def _sample_normal(params, mu, sigma, key):
    s = _shape(params)
    z = jax.random.normal(key, mu.shape + s, dtype=_dt(params))
    return mu.reshape(mu.shape + (1,) * len(s)) + z * sigma.reshape(
        sigma.shape + (1,) * len(s))


@register("_sample_gamma", nin=2, needs_rng=True,
          params={"shape": (), "dtype": "float32"})
def _sample_gamma(params, alpha, beta, key):
    s = _shape(params)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(key, jnp.broadcast_to(a, alpha.shape + s), dtype=_dt(params))
    return g * beta.reshape(beta.shape + (1,) * len(s))


def _multinomial_nout(params):
    return 2 if params.get("get_prob") else 1


@register("_sample_multinomial", nout=_multinomial_nout, needs_rng=True,
          params={"shape": (), "get_prob": False, "dtype": "int32"})
def _sample_multinomial(params, data, key):
    """Reference sample_multinomial_op.cc: data (..., K) of probabilities;
    draws prod(shape) categorical samples per distribution row."""
    s = _shape(params)
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-37))
    flat = logits.reshape(-1, logits.shape[-1])
    keys = jax.random.split(key, flat.shape[0])
    samp = jax.vmap(lambda k, lg: jax.random.categorical(k, lg, shape=(n,)))(
        keys, flat)                                    # (rows, n)
    out_shape = data.shape[:-1] + s                    # () shape -> one draw/row
    samples = samp.reshape(out_shape).astype(params.get("dtype") or "int32")
    if params.get("get_prob"):
        oh = jax.nn.one_hot(samples.astype("int32"), data.shape[-1])
        if s:
            # oh: (..., *s, K) vs logits (..., K): broadcast over sample dims
            lg = logits.reshape(data.shape[:-1] + (1,) * len(s) + (data.shape[-1],))
            lp = jnp.sum(oh * lg, axis=-1)
        else:
            lp = jnp.sum(oh * logits, axis=-1)
        return samples, lp
    return samples


@register("_shuffle", needs_rng=True, aliases=("shuffle",))
def _shuffle(params, x, key):
    """Shuffle along the first axis (reference shuffle_op.cc)."""
    perm = jax.random.permutation(key, x.shape[0])
    return jnp.take(x, perm, axis=0)
