"""CTC loss (reference `src/operator/contrib/ctc_loss.cc` over bundled
warpctc).

TPU-native: the CTC forward (alpha) recursion in log space as a `lax.scan`
over time — fully jax-traceable, so the gradient comes from autodiff of the
log-sum-exp recursion (warpctc's hand-written backward is the same quantity).
blank = 0 ('first', the MXNet default).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

NEG = -1e30


def _ctc_single(logp, labels, input_len, label_len):
    """loss for one sequence.  logp: (T, C) log-probs; labels: (L,) int32."""
    T, C = logp.shape
    L = labels.shape[0]
    S = 2 * L + 1
    # extended label sequence [blank, l0, blank, l1, ..., blank]
    ext = jnp.zeros((S,), dtype=jnp.int32)
    ext = ext.at[1::2].set(labels)
    s_idx = jnp.arange(S)
    valid_s = s_idx < (2 * label_len + 1)

    # can skip from s-2 if ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
    can_skip = (ext != 0) & (ext != ext_m2)

    alpha0 = jnp.full((S,), NEG)
    alpha0 = alpha0.at[0].set(logp[0, 0])
    alpha0 = alpha0.at[1].set(jnp.where(label_len > 0, logp[0, ext[1]], NEG))

    def step(alpha, t):
        a0 = alpha
        a1 = jnp.concatenate([jnp.full((1,), NEG), alpha[:-1]])
        a2 = jnp.where(can_skip,
                       jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]]),
                       NEG)
        m = jnp.maximum(jnp.maximum(a0, a1), a2)
        new = m + jnp.log(jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
        new = new + logp[t, ext]
        new = jnp.where(valid_s, new, NEG)
        # freeze beyond input_len
        new = jnp.where(t < input_len, new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = alpha[jnp.maximum(2 * label_len, 0)]
    end2 = jnp.where(label_len > 0, alpha[jnp.maximum(2 * label_len - 1, 0)],
                     NEG)
    m = jnp.maximum(end1, end2)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    return -ll


@register("ctc_loss", nin=-1,
          aliases=("CTCLoss", "_contrib_ctc_loss", "_contrib_CTCLoss"),
          params={"use_data_lengths": False, "use_label_lengths": False,
                  "blank_label": "first"})
def _ctc_loss(params, data, label, *rest):
    """data: (T, N, C) activations (softmax applied internally, as warpctc);
    label: (N, L) padded with 0; optional data_lengths (N,), label_lengths (N,)."""
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    idx = 0
    if params["use_data_lengths"]:
        data_lens = rest[idx].astype("int32")
        idx += 1
    else:
        data_lens = jnp.full((N,), T, jnp.int32)
    labels = label.astype("int32")
    if params["use_label_lengths"]:
        label_lens = rest[idx].astype("int32")
    else:
        # padding value 0 terminates the label (blank_label='first')
        label_lens = jnp.sum((labels > 0).astype(jnp.int32), axis=1)

    logp_n = jnp.swapaxes(logp, 0, 1)  # (N, T, C)
    return jax.vmap(_ctc_single)(logp_n, labels, data_lens, label_lens)
