"""Internal NHWC execution layout for spatial ops.

The reference gets layout-optimized kernels from cuDNN autotune
(`src/operator/nn/cudnn/`, `docs/faq/env_var.md:154`) and MKLDNN's opaque
blocked layouts (`src/operator/nn/mkldnn/mkldnn_base-inl.h`): the API
speaks NCHW, the kernels run whatever layout the hardware prefers, and
reorders happen at subgraph edges.  The TPU MXU strongly prefers
channels-minor (NHWC) convolutions; this module is the TPU reading of the
same idea — a graph-level rewrite used by the executor
(`symbol/symbol.py graph_eval_fn`) that:

* runs Convolution / Pooling / BatchNorm natively in NHWC,
* lets elementwise ops flow NHWC through unchanged,
* transposes back to the API's NCHW at every other consumer and at graph
  heads, so results are bit-identical module the usual float reassociation.

Measured on one v5e chip (ResNet-50 train, batch 128, bf16,
same-process A/B, tools/perf_decomp.py): a hand-written NHWC control is
only ~0.5-3% faster than the NCHW control (XLA's layout assignment
already tiles NCHW convolutions onto the MXU well), and the framework
graph is ~3% SLOWER in NHWC because the per-step OIHW->HWIO weight
transposes cost more than the layout buys.  Cross-process runs differ by
up to ±13% on the tunnel-fronted chip, which is how NHWC first looked
like a big win.  The pass therefore ships DISABLED by default; the
cuDNN/MKLDNN layout-selection role is subsumed by XLA layout assignment
on TPU.

Enable with ``MXNET_INTERNAL_CONV_LAYOUT=NHWC`` (exact, bit-stable
results either way).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .nn import _tup, _batch_norm

__all__ = ["enabled", "to_nhwc", "to_nchw", "NATIVE", "AGNOSTIC",
           "layout_safe_input"]


def enabled():
    from .. import config as _config
    return str(_config.get("MXNET_INTERNAL_CONV_LAYOUT")).upper() == "NHWC"


def to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _conv_nhwc(params, x, weight, *rest):
    """2-D Convolution on NHWC activations; weight stays OIHW at the API
    (checkpoints unchanged), transposed to HWIO inside the program (XLA
    folds the small weight transpose into its own layout assignment)."""
    stride = _tup(params["stride"], 2, 1)
    dilate = _tup(params["dilate"], 2, 1)
    pad = _tup(params["pad"], 2, 0)
    w = jnp.transpose(weight, (2, 3, 1, 0)).astype(x.dtype)  # OIHW -> HWIO
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(p, p) for p in pad],
        lhs_dilation=(1, 1), rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=int(params["num_group"]))
    if not params["no_bias"]:
        out = out + rest[0].astype(out.dtype).reshape((1, 1, 1, -1))
    return out


def _pooling_nhwc(params, x):
    """2-D Pooling on NHWC (mirrors ops/nn.py _pooling exactly, windows on
    axes 1-2)."""
    if params["global_pool"]:
        if params["pool_type"] == "max":
            return jnp.max(x, axis=(1, 2), keepdims=True)
        red = jnp.sum if params["pool_type"] == "sum" else jnp.mean
        return red(x, axis=(1, 2), keepdims=True)
    kernel = _tup(params["kernel"], 2, 1)
    stride = _tup(params["stride"], 2, 1)
    pad = _tup(params["pad"], 2, 0)
    ceil_mode = params["pooling_convention"] == "full"
    pads = []
    for i in range(2):
        lo = hi = pad[i]
        if ceil_mode:
            size = x.shape[1 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                hi += stride[i] - rem
        pads.append((lo, hi))
    window = (1,) + kernel + (1,)
    strides = (1,) + stride + (1,)
    full_pads = [(0, 0)] + pads + [(0, 0)]
    ptype = params["pool_type"]
    if ptype == "max":
        if jnp.issubdtype(x.dtype, jnp.floating):
            init = np.array(-np.inf, x.dtype)[()]
        else:
            init = np.array(np.iinfo(np.dtype(x.dtype)).min, x.dtype)[()]
        return jax.lax.reduce_window(x, init, jax.lax.max,
                                     window, strides, full_pads)
    if ptype in ("avg", "sum"):
        s = jax.lax.reduce_window(x, np.zeros((), x.dtype)[()], jax.lax.add,
                                  window, strides, full_pads)
        if ptype == "sum":
            return s
        if params["count_include_pad"]:
            denom = 1
            for k in kernel:
                denom *= k
            return s / jnp.asarray(denom, x.dtype)
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, jnp.asarray(0, x.dtype),
                                    jax.lax.add, window, strides, full_pads)
        return s / jnp.maximum(cnt, 1)


def _batch_norm_nhwc(params, x, gamma, beta, moving_mean, moving_var):
    """BatchNorm over the trailing channel axis (the op already supports an
    axis parameter; NHWC just remaps the default channel position)."""
    return _batch_norm(dict(params, axis=3), x, gamma, beta,
                       moving_mean, moving_var)


def _native_ok(opname, params, x):
    """Can this node run its NHWC variant for input `x`?"""
    if getattr(x, "ndim", 0) != 4:
        return False
    if opname == "Convolution":
        return len(tuple(params["kernel"])) == 2 and not params.get("layout")
    if opname in ("Pooling", "Pooling_v1"):
        if params["pool_type"] not in ("max", "avg", "sum"):
            return False    # NCHW fn validates and raises loudly
        return params["global_pool"] or len(_tup(params["kernel"], 2, 1)) == 2
    if opname in ("BatchNorm", "BatchNorm_v1"):
        return int(params.get("axis", 1)) == 1
    return False


# NHWC-native executors: same (params, *arrays) contract as the registered
# fn, but expecting/producing NHWC activations
NATIVE = {
    "Convolution": (_conv_nhwc, _native_ok),
    "Pooling": (_pooling_nhwc, _native_ok),
    "Pooling_v1": (_pooling_nhwc, _native_ok),
    "BatchNorm": (_batch_norm_nhwc, _native_ok),
    "BatchNorm_v1": (_batch_norm_nhwc, _native_ok),
}

# Elementwise ops through which an NHWC tag flows unchanged.  An op may
# pass only if every array input is layout-safe (see layout_safe_input):
# broadcasting a (C,) or (1,C,1,1)-shaped operand against NHWC data would
# hit the wrong axis.
AGNOSTIC = frozenset({
    "Activation", "LeakyReLU", "relu", "sigmoid", "tanh", "softsign",
    "Dropout", "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "_plus", "_sub", "_mul", "_div", "_add",
    "_plus_scalar", "_minus_scalar", "_mul_scalar", "_div_scalar",
    "_rminus_scalar", "_rdiv_scalar", "_power_scalar",
    "clip", "abs", "exp", "log", "sqrt", "square", "negative",
    "_identity", "BlockGrad", "identity", "_copy",
})


def layout_safe_input(v, tag):
    """True when value `v` (with layout tag `tag`, 'NHWC' or None) can feed
    an AGNOSTIC op alongside NHWC operands without changing semantics."""
    nd = getattr(v, "ndim", None)
    if nd is None:
        return True          # python scalar
    if nd == 0:
        return True
    if nd == 4:
        return tag == "NHWC"
    # non-4d arrays broadcast against trailing axes — only all-singleton
    # shapes are layout-neutral
    return all(d == 1 for d in getattr(v, "shape", ()))
