"""Central operator registry.

TPU-native equivalent of the reference's NNVM op registry
(`NNVM_REGISTER_OP` + string-keyed attribute maps, `include/mxnet/op_attr_types.h:66-271`,
example registration `src/operator/nn/fully_connected.cc:239-328`).

Design: one registry entry per operator.  Instead of separate
`FCompute<cpu>` / `FCompute<gpu>` kernels plus hand-written `FInferShape` /
`FInferType` / `FGradient` tables, each op provides a single **pure,
jax-traceable compute function** ``fn(params, *arrays) -> array | tuple``:

* eager dispatch jit-compiles it per (op, static-params) — XLA generates the
  TPU kernel (the `FCompute<tpu>` equivalent);
* shape/type inference is `jax.eval_shape` of the same function (replaces the
  InferAttr fixpoint, `src/executor/infer_graph_attr_pass.cc:73`);
* gradients come from `jax.vjp` of the same function (replaces `FGradient`);
  ops with non-autodiff gradients (e.g. SoftmaxOutput's implicit CE loss grad,
  `src/operator/softmax_output.cc`) wrap themselves in `jax.custom_vjp`;
* the symbolic executor composes these functions into one XLA computation
  (replaces `GraphExecutor` + bulk segments, `src/executor/graph_executor.cc`).

The Python frontends are *generated* from this registry
(`ndarray/register.py`, `symbol/register.py`) exactly like the reference
generates them from `MXSymbolListAtomicSymbolCreators`
(`python/mxnet/ndarray/register.py:30-169`).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

from ..base import MXNetError, py_literal

__all__ = ["OpDef", "register", "get", "list_ops", "REQUIRED", "eager_call",
           "vjp_call", "eval_shape"]


class _Required:
    def __repr__(self):
        return "REQUIRED"


REQUIRED = _Required()

_REGISTRY: dict[str, "OpDef"] = {}


class OpDef:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (reference ops keep their MXNet names so that
        generated frontends and saved Symbol JSON stay compatible).
    fn : ``fn(params: dict, *arrays) -> jnp.ndarray | tuple`` pure function.
    nin : number of tensor inputs; -1 = variadic (count from ``variadic_param``).
    nout : number of outputs, or callable ``(params) -> int``.
    naux : trailing inputs that are auxiliary states (e.g. BatchNorm running
        stats); in train mode ``fn`` returns ``nout`` outputs followed by
        ``naux`` updated aux values which the caller writes back in place.
    params : dict name -> default (REQUIRED for mandatory params).
    param_types : optional dict name -> converter applied after coercion.
    needs_rng : op consumes a PRNG key; dispatch appends a key array input.
    mode_dependent : op behaves differently in train vs predict mode; dispatch
        injects boolean param ``_train``.
    stop_grad : do not record on the autograd tape (BlockGrad & friends).
    aliases : alternative registered names (reference keeps e.g. both
        ``Flatten`` and ``flatten``).
    """

    __slots__ = ("name", "fn", "nin", "nout", "naux", "params", "param_types",
                 "needs_rng", "mode_dependent", "stop_grad", "aliases",
                 "variadic_param", "dynamic_params", "input_names", "doc",
                 "cache_key", "cost_meta")

    def __init__(self, name, fn, nin=1, nout=1, naux=0, params=None,
                 param_types=None, needs_rng=False, mode_dependent=False,
                 stop_grad=False, aliases=(), variadic_param=None,
                 dynamic_params=(), input_names=None, doc=None,
                 cache_key=None, cost_meta=None):
        self.name = name
        self.fn = fn
        self.nin = nin
        self.nout = nout
        self.naux = naux
        self.params = dict(params or {})
        self.param_types = dict(param_types or {})
        self.needs_rng = needs_rng
        self.mode_dependent = mode_dependent
        self.stop_grad = stop_grad
        self.aliases = tuple(aliases)
        self.variadic_param = variadic_param
        # dynamic_params: params passed as traced scalar inputs (appended after
        # tensor inputs, before the rng key) so e.g. a changing learning rate
        # does not retrigger XLA compilation.
        self.dynamic_params = tuple(dynamic_params)
        # input_names: static list or callable(params)->list of input slot
        # names; the symbolic frontend auto-creates Variables for trailing
        # missing inputs (reference ListArguments + auto-var creation in
        # Symbol composition, e.g. fc1_weight/fc1_bias)
        self.input_names = input_names
        self.doc = doc or (fn.__doc__ if fn else None)
        # cache_key: a process-stable graph identity (e.g. a symbol-JSON
        # hash for CachedOp graphs) routing this op's eager dispatch
        # through the unified program cache's disk tier; None (all
        # primitive ops) keeps the plain per-(op, params) jit — tiny
        # programs that are not worth a disk round trip.
        self.cache_key = cache_key
        # cost_meta: static metadata for the mxcost analyzer
        # (analysis/cost.py).  Keys: "flops" — fn(params, in_avals,
        # out_avals) -> float overriding the analyzer's per-op-name
        # rule; "compute_dtype" — the dtype the op's arithmetic ACTUALLY
        # runs in, when it differs from what the graph dtypes suggest
        # (the quantized ops declare "float32" here: that declaration IS
        # the int8-slower-than-fp32 defect's static signature);
        # "quantized" — marks an int8-family op for the dtype-flow pass.
        self.cost_meta = dict(cost_meta) if cost_meta else None

    # -- parameter handling ---------------------------------------------------
    def canonicalize_params(self, kwargs):
        """Coerce/validate kwargs against the param table; returns plain dict."""
        out = {}
        for k, default in self.params.items():
            if k in kwargs and kwargs[k] is not None:
                v = py_literal(kwargs[k])
                conv = self.param_types.get(k)
                if conv is not None:
                    v = conv(v)
                out[k] = _hashable(v)
            elif default is REQUIRED:
                raise MXNetError(
                    f"Operator {self.name}: required parameter '{k}' missing")
            else:
                out[k] = _hashable(default)
        unknown = set(kwargs) - set(self.params) - {"name", "out", "ctx", "attr", "__layout__", "lr_mult", "wd_mult"}
        if unknown:
            raise MXNetError(f"Operator {self.name}: unknown parameters {sorted(unknown)}")
        return out

    def num_outputs(self, params):
        return self.nout(params) if callable(self.nout) else self.nout

    def num_aux(self, params):
        return self.naux(params) if callable(self.naux) else self.naux

    def list_input_names(self, params):
        if self.input_names is None:
            return None
        if callable(self.input_names):
            return list(self.input_names(params))
        return list(self.input_names)

    def num_inputs(self, params):
        if self.nin >= 0:
            return self.nin
        if self.variadic_param and self.variadic_param in params:
            return int(params[self.variadic_param])
        return -1

    def __repr__(self):
        return f"OpDef({self.name})"


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def register(name, **kwargs):
    """Decorator registering a compute function as operator ``name``.

    Mirrors `NNVM_REGISTER_OP(name).set_attr<FCompute>(...)` — but there is a
    single backend (XLA) so one function covers cpu+tpu.
    """
    def deco(fn):
        op = OpDef(name, fn, **kwargs)
        if name in _REGISTRY:
            raise MXNetError(f"Operator {name} registered twice")
        _REGISTRY[name] = op
        for alias in op.aliases:
            if alias in _REGISTRY:
                raise MXNetError(f"Operator alias {alias} registered twice")
            _REGISTRY[alias] = op
        return fn
    return deco


def register_opdef(op):
    """Register a dynamically-created OpDef (CachedOp graphs)."""
    _REGISTRY[op.name] = op
    return op


def get(name) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(f"Operator {name} is not registered") from None


def maybe_get(name) -> Optional[OpDef]:
    return _REGISTRY.get(name)


def list_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Eager dispatch: jit-per-(op, params) cache.  The analogue of the reference's
# imperative PushFCompute (`src/imperative/imperative_utils.h:361-410`): one
# cached XLA executable per (op, static attrs, input signature) — jax.jit
# handles the per-signature level.
# ---------------------------------------------------------------------------

def _freeze(v):
    """Hashable stand-in for a param value: the jit caches key on frozen
    params, and basic-index keys carry `slice` objects, which are
    unhashable before Python 3.12."""
    if isinstance(v, slice):
        return ("__slice__", v.start, v.stop, v.step)
    if isinstance(v, tuple):
        return tuple(_freeze(x) for x in v)
    return v


def _thaw(v):
    if isinstance(v, tuple):
        if len(v) == 4 and v[0] == "__slice__":
            return slice(v[1], v[2], v[3])
        return tuple(_thaw(x) for x in v)
    return v


def _freeze_params(params):
    return tuple(sorted((k, _freeze(v)) for k, v in params.items()))


@functools.lru_cache(maxsize=None)
def _jitted(op_name, frozen_params):
    import jax
    op = _REGISTRY[op_name]
    params = {k: _thaw(v) for k, v in frozen_params}

    def run(*arrays):
        return op.fn(params, *arrays)

    if op.cache_key is not None:
        # whole-graph ops (Gluon CachedOp) compile through the unified
        # program cache: a fresh process loads the serialized executable
        # from the disk tier instead of re-paying the XLA compile
        from ..compile import cached_jit
        return cached_jit(run,
                          graph_key=("cachedop", op.cache_key,
                                     frozen_params),
                          label="cachedop/" + op_name)
    return jax.jit(run)


def eager_call(op: OpDef, params: dict, arrays):
    """Execute an op eagerly; returns tuple of jax arrays (outputs then aux).

    Inside an outer jax trace (fused train step / CachedOp), the compute
    function is called directly: nesting a jit per op would bloat the outer
    program with hundreds of call-ops and multiply compile time.
    """
    import jax
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        out = op.fn(dict(params), *arrays)
    else:
        out = _jitted(op.name, _freeze_params(params))(*arrays)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return tuple(out)


@functools.lru_cache(maxsize=None)
def _jitted_vjp(op_name, frozen_params):
    import jax
    op = _REGISTRY[op_name]
    params = {k: _thaw(v) for k, v in frozen_params}

    def run(arrays, cotangents):
        import jax.numpy as jnp

        def fwd(*xs):
            out = op.fn(params, *xs)
            return out if isinstance(out, tuple) else (out,)
        primals, vjp = jax.vjp(fwd, *arrays)
        # ops may emit trailing aux-state outputs (e.g. BatchNorm running
        # stats in train mode) that carry no gradient: pad with zeros
        cts = tuple(cotangents) + tuple(
            jnp.zeros_like(p) for p in primals[len(cotangents):])
        return vjp(cts)

    return jax.jit(run)


def vjp_call(op: OpDef, params: dict, arrays, cotangents):
    """Input gradients of an op at ``arrays`` given output ``cotangents``.

    The `FGradient` equivalent (`include/mxnet/op_attr_types.h` FGradient):
    computed from the same compute function via jax.vjp, compiled and cached.
    """
    return _jitted_vjp(op.name, _freeze_params(params))(tuple(arrays),
                                                        tuple(cotangents))


def eval_shape(op: OpDef, params: dict, avals):
    """Shape/dtype inference (replaces InferShape/InferType fixpoint,
    `src/executor/infer_graph_attr_pass.cc:35-262`) via jax.eval_shape."""
    import jax

    def run(*xs):
        out = op.fn(params, *xs)
        return out if isinstance(out, tuple) else (out,)

    return jax.eval_shape(run, *avals)
