"""Reduction and broadcasting-structure ops.

Reference: `src/operator/tensor/broadcast_reduce_op_{value,index}.cc`
(sum/mean/prod/nansum/nanprod/max/min/norm/argmax/argmin/broadcast_to/
broadcast_axis).  MXNet reduce semantics: ``axis`` may be None / int / tuple,
``exclude=True`` reduces over the complement, ``keepdims`` keeps reduced dims.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

_REDUCE_PARAMS = {"axis": None, "keepdims": False, "exclude": False}


def _norm_axis(params, ndim):
    axis = params.get("axis", None)
    if axis is None or axis == () or axis == []:
        axes = tuple(range(ndim))
        if params.get("exclude", False):
            axes = ()
        return axes
    if isinstance(axis, int):
        axis = (axis,)
    axes = tuple(a % ndim for a in axis)
    if params.get("exclude", False):
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _make_reduce(f):
    def fn(params, x):
        axes = _norm_axis(params, x.ndim)
        if axes == ():
            return x + 0 if f is not jnp.nansum and f is not jnp.nanprod else jnp.nan_to_num(x)
        return f(x, axis=axes, keepdims=bool(params.get("keepdims", False)))
    return fn


for _name, _f, _aliases in [
    ("sum", jnp.sum, ("sum_axis",)),
    ("mean", jnp.mean, ()),
    ("prod", jnp.prod, ()),
    ("nansum", jnp.nansum, ()),
    ("nanprod", jnp.nanprod, ()),
    ("max", jnp.max, ("max_axis",)),
    ("min", jnp.min, ("min_axis",)),
]:
    register(_name, nin=1, params=dict(_REDUCE_PARAMS), aliases=_aliases)(_make_reduce(_f))


@register("norm", params={"ord": 2, "axis": None, "keepdims": False, "out_dtype": None})
def _norm(params, x):
    """Reference `broadcast_reduce_op_value.cc` norm (L1/L2)."""
    ordv = int(params["ord"])
    axis = params["axis"]
    if isinstance(axis, int):
        axis = (axis,)
    keepdims = bool(params["keepdims"])
    if ordv == 1:
        out = jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    elif ordv == 2:
        out = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))
    else:
        raise ValueError("norm only supports ord=1 or 2 (as the reference)")
    if params["out_dtype"]:
        out = out.astype(params["out_dtype"])
    return out


def _make_arg(f):
    def fn(params, x):
        axis = params.get("axis", None)
        keepdims = bool(params.get("keepdims", False))
        if axis is None:
            out = f(x.reshape(-1), axis=0)
            out = out.astype("float32")
            return out.reshape((1,) * x.ndim) if keepdims else out
        out = f(x, axis=int(axis)).astype("float32")
        if keepdims:
            out = jnp.expand_dims(out, int(axis))
        return out
    return fn


# MXNet argmax/argmin return float dtype (reference broadcast_reduce_op_index.cc)
register("argmax", nin=1, params={"axis": None, "keepdims": False})(_make_arg(jnp.argmax))
register("argmin", nin=1, params={"axis": None, "keepdims": False})(_make_arg(jnp.argmin))


@register("argmax_channel")
def _argmax_channel(params, x):
    return jnp.argmax(x, axis=1).astype("float32")


@register("broadcast_to", params={"shape": ()})
def _broadcast_to(params, x):
    tgt = tuple(params["shape"])
    # 0 entries mean "keep input size" in the reference
    tgt = tuple(x.shape[i] if t == 0 else t for i, t in enumerate(tgt))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", params={"axis": (), "size": ()}, aliases=("broadcast_axes",))
def _broadcast_axis(params, x):
    axes = params["axis"]
    sizes = params["size"]
    if isinstance(axes, int):
        axes = (axes,)
    if isinstance(sizes, int):
        sizes = (sizes,)
    shape = list(x.shape)
    for a, s in zip(axes, sizes):
        shape[a % x.ndim] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("broadcast_like", nin=2)
def _broadcast_like(params, x, like):
    return jnp.broadcast_to(x, like.shape)
